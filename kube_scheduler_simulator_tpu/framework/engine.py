"""Scheduling engine: drives the tensor pipeline against the cluster store.

This is the in-process equivalent of the reference's debuggable-scheduler
process (SURVEY.md §3.2): it takes pending pods from the cluster, runs the
batched Filter/Score program, binds the chosen nodes, deposits the decoded
result annotations in the result store, and triggers the reflector —
replacing the informer round-trip of the reference (storereflector
registers a Pod-update handler; binding IS the update that triggers it).

Queue order follows the PrioritySort queue-sort plugin: descending
.spec.priority, FIFO within equal priority (upstream
pkg/scheduler/framework/plugins/queuesort).  Unschedulable pods get the
PodScheduled=False/Unschedulable condition, like the scheduler's status
update, which also carries their result annotations out.
"""

from __future__ import annotations

import copy
import functools
import os
import time

import numpy as np

from .replay import replay
from ..cluster.store import Conflict, NotFound, ObjectStore
from ..utils.tracing import TRACER
from ..plugins.registry import PluginSetConfig
from ..state.compile import compile_workload
from ..store import annotations as ann
from ..store.decode import decode_pod_result
from ..store.reflector import StoreReflector
from ..store.resultstore import ResultStore

RESULT_STORE_KEY = "PluginResultStoreKey"      # reference: plugins.go:23
EXTENDER_STORE_KEY = "ExtenderResultStoreKey"  # reference: extender/service.go:24
DEFAULT_SCHEDULER_NAME = "default-scheduler"


class _LazyDecode:
    """list-like view decoding each pod's annotations on first access."""

    def __init__(self, rr):
        self.rr = rr

    def __getitem__(self, i):
        return decode_pod_result(self.rr, i)


class _ReflectBatcher:
    """Chunked async reflect write-backs, shared by the sequential
    post-pass and the pipelined committer so their batching and error
    semantics cannot diverge: ~batch_n pods per pool future; every pod
    in a batch is attempted even if an earlier one fails, and the first
    error surfaces from drain().

    use_batch routes through StoreReflector.reflect_batch (the
    apply_batch surface) — the committer's mode; the sequential
    post-pass keeps per-pod reflect() (its pre-change mechanism, and
    the parity baseline)."""

    def __init__(self, engine: "SchedulerEngine", n_pending: int,
                 use_batch: bool):
        self._pool = engine._reflector_pool()
        # small waves still fan across the pool; 10k-pod waves cost ~150
        # futures instead of 10k
        self._batch_n = max(1, min(64, n_pending // 8))
        self._batch: list[tuple[str, str, str | None]] = []
        self._futs: list = []
        fn = getattr(engine.reflector, "reflect_batch", None) if use_batch \
            else None
        if fn is None:
            from ..store.reflector import reflect_each

            reflect_one = engine.reflector.reflect

            def fn(batch):
                reflect_each(reflect_one, batch)
        self._fn = fn

    def submit(self, ns: str, name: str, uid: str | None) -> None:
        self._batch.append((ns, name, uid))
        if len(self._batch) >= self._batch_n:
            self._futs.append(self._pool.submit(self._fn, self._batch[:]))
            self._batch.clear()

    def drain(self) -> None:
        if self._batch:
            self._futs.append(self._pool.submit(self._fn, self._batch[:]))
            self._batch.clear()
        for f in self._futs:
            f.result()


class _GangParked:
    """A gang member parked by the vectorized quorum pass: its assumed
    node (the speculative assignment rolled back to waiting), the
    group it waits for, and the timeout that rejects the whole gang."""

    __slots__ = ("ns", "name", "uid", "node", "group", "deadline",
                 "timeout_str", "seq")

    def __init__(self, ns, name, uid, node, group, deadline, timeout_str, seq):
        self.ns = ns
        self.name = name
        self.uid = uid
        self.node = node
        self.group = group
        self.deadline = deadline
        self.timeout_str = timeout_str
        self.seq = seq


class _GangCtx:
    """Per-wave gang state for the vectorized admission pass
    (docs/gang-scheduling.md): the pod→group id vector the quorum
    segment-reduction runs over, per-group specs, and the
    waiting+bound counts frozen at wave start."""

    __slots__ = ("gp_name", "keys", "gid", "min_member", "already",
                 "timeout_s", "timeout_str", "start", "last",
                 "admitted_before", "counted", "pending")

    def __init__(self, gp_name: str, pending: list[dict], directory,
                 parked_counts: dict):
        import numpy as np

        from .gang import group_key_of

        self.gp_name = gp_name
        self.pending = pending
        self.keys: list[tuple[str, str]] = []
        self.timeout_s: list[float] = []
        self.timeout_str: list[str] = []
        n = len(pending)
        self.gid = np.full(n, -1, dtype=np.int32)
        ids: dict[tuple[str, str], int] = {}
        start: list[int] = []
        last: list[int] = []
        mins: list[int] = []
        already: list[int] = []
        for i, p in enumerate(pending):
            key = group_key_of(p)
            if key is None:
                continue
            spec = directory.specs.get(key)
            if spec is None:
                continue  # label without a PodGroup: ordinary pod
            g = ids.get(key)
            if g is None:
                g = ids[key] = len(self.keys)
                self.keys.append(key)
                self.timeout_s.append(spec.timeout_seconds)
                self.timeout_str.append(spec.timeout_str)
                mins.append(spec.min_member)
                already.append(parked_counts.get(key, 0)
                               + directory.bound.get(key, 0))
                start.append(i)
                last.append(i)
            self.gid[i] = g
            last[g] = i
        self.min_member = np.asarray(mins, dtype=np.int32)
        self.already = np.asarray(already, dtype=np.int32)
        self.start = np.asarray(start, dtype=np.int32)
        self.last = np.asarray(last, dtype=np.int32)
        self.admitted_before = [directory.bound.get(k, 0) > 0
                                for k in self.keys]
        self.counted: set[int] = set()

    def __bool__(self) -> bool:
        return bool(self.keys)


class _NoGang:
    """Falsy wave sentinel: the gang plugin is enabled and handled by
    the engine this wave (so the custom-lifecycle set excludes it), but
    no group has members in the wave — every commit path runs its
    plain, gang-free code."""

    def __bool__(self) -> bool:
        return False


_GANG_NONE = _NoGang()

# the degradation ladder's rungs (docs/fault-injection.md): all three
# are bit-identical parity gates (tests/test_device_resident.py), so
# stepping down after a structural device fault is provably lossless —
# it trades wall time (host fetch, eager decode) for survival
_RESIDENCY_MODES = ("device_resident", "host_resident", "eager_decode")


class _WaveAbort(Exception):
    """Internal: a wave attempt failed mid-flight.  Carries the
    UNCOMMITTED SUFFIX of the attempt's (filtered: exclude/gates/gang
    prescreen already applied) pending list — everything before it
    landed: binds stand, gang state is consistent at the commit
    boundary — and the binds already counted, so the wave failure
    protocol retries exactly the suffix and returns an accurate bound
    total (docs/fault-injection.md).  The suffix is the filtered list
    itself, not an index into the caller's pending: the attempt
    filters before committing, so outer indices would misalign."""

    def __init__(self, cause: BaseException, remaining: list,
                 n_bound: int, stage: str):
        super().__init__(f"wave aborted at {stage}: "
                         f"{type(cause).__name__}: {cause}")
        self.cause = cause
        self.remaining = remaining
        self.n_bound = n_bound
        self.stage = stage


class _WaveCommitter:
    """Chunk-pipelined commit consumer for a streaming wave.

    replay(on_chunk=...) delivers decoded chunks in ascending pod order
    while the device scans later chunks; on_chunk (replay thread) decodes
    the chunk and hands it to a single worker thread that runs the commit
    phase — result-store puts, batched binds / unschedulable marks
    (ObjectStore.apply_batch), reflect submissions — in pod order.  The
    single worker preserves the sequential path's per-pod ordering, so
    annotations, bind order and result-history are bit-identical to the
    post-pass (tests/test_golden_annotations.py parity gate).

    Width-tier reruns: a score overflow makes replay() re-deliver chunks
    from index 0 at a wider dtype.  Chunks that were ingested WITHOUT the
    overflow flag are bit-identical across tiers (pipeline.py compares
    the full-precision scores against the narrowed transfer before
    setting the flag), so the worker keeps a committed-up-to watermark
    and skips re-delivered pods instead of double-committing them.

    The commit time spent while the device was still scanning is
    reported as the commit_stream_overlap_seconds counter; the
    commit_and_reflect span covers only the post-replay tail (what the
    wave still serializes on)."""

    def __init__(self, engine: "SchedulerEngine", node_names, pending,
                 gang: "_GangCtx | None" = None, lazy: bool = False):
        import queue
        import threading

        self.engine = engine
        self.node_names = node_names
        self.pending = pending
        self.annotations: list = [None] * len(pending)
        # lazy mode (store/lazy.py): on_chunk skips the decode entirely —
        # the commit consumes TENSOR-LEVEL decisions (selected/gang
        # quorum) and deposits LazyWave handles; annotations materialize
        # on first read, off the wave's critical path
        self.lazy = lazy
        self._waves: list = []     # one LazyWave per width-tier replay run
        self._cur_rr = None
        # gang ranges can span chunks from two width tiers: remember the
        # wave each pod's chunk was delivered by (byte-identical across
        # tiers for delivered chunks, but exactness is free)
        self._pod_wave: list | None = (
            [None] * len(pending) if (lazy and gang) else None)
        self.n_bound = 0
        # gang-atomic streaming (docs/gang-scheduling.md): commit ranges
        # are cut on gang boundaries — a gang straddling the chunk edge
        # defers to the next chunk's commit (or the wave's tail), so the
        # quorum decision always sees the whole gang
        self.gang = gang if gang else None
        self._selected = (np.full(len(pending), -2, dtype=np.int32)
                          if self.gang is not None else None)
        # wave span id set by the engine once the replay span opens, so
        # the worker's commit_stream spans parent under it across the
        # thread boundary (utils/tracing.py span tree)
        self.parent_span: int | None = None
        self._upto = 0          # pods [0, _upto) already committed
        self._busy: list[tuple[float, float]] = []
        self._exc: BaseException | None = None
        self._stop = False      # abort(): drop queued chunks uncommitted
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._reflects = _ReflectBatcher(engine, len(pending), use_batch=True)
        # the worker inherits the engine's session scope (its own thread:
        # thread-local scopes don't cross the boundary by themselves)
        self._session = getattr(engine, "session", None)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="commit-stream")
        self._thread.start()

    # ---------------------------------------------- replay-thread side

    def on_chunk(self, rr, lo: int, hi: int) -> None:
        import numpy as np

        wave = None
        if self.lazy:
            # chunk HANDOFF only: no decode on the replay thread — a
            # width-tier rerun delivers a fresh ReplayResult, which gets
            # its own LazyWave (already-committed pods keep handles into
            # the old one; delivered chunks are bit-identical across
            # tiers, replay() contract)
            if self._cur_rr is not rr:
                from ..store.lazy import LazyWave
                from .replay import ChunkAttribution

                self._cur_rr = rr
                w = LazyWave(rr, len(self.pending))
                # per-plugin attribution tallies on the commit worker,
                # chunk by chunk, overlapped with the device scan — off
                # the wave tail (framework/replay.py ChunkAttribution)
                w._attr_acc = ChunkAttribution(rr)
                self._waves.append(w)
            wave = self._waves[-1]
        else:
            # the WHOLE chunk goes down in one call: decode_chunk_into
            # routes it through the chunk-granular native decode (one
            # GIL-released C call per chunk, C-side worker pool)
            from ..store.decode import decode_chunk_into

            decode_chunk_into(rr, lo, hi, self.annotations)
        self._q.put((wave, lo, hi, np.asarray(rr.selected[lo:hi]).copy()))

    def finish(self) -> tuple[int, None]:
        """Replay drained: commit the remaining chunks, settle reflects,
        surface worker errors.  -> (#bound, None)."""
        replay_end = time.perf_counter()
        self._q.put(None)
        with TRACER.span("commit_and_reflect", pods=len(self.pending)) as sp:
            self._thread.join()
            for w in self._waves:
                w.seal()  # replay drained: deferred reads may decode
            if self._exc is None:
                self._reflects.drain()
        TRACER.observe("framework_extension_point_duration_seconds",
                       sp.seconds, extension_point="bind")
        overlap = sum(max(0.0, min(t1, replay_end) - t0)
                      for t0, t1 in self._busy if t0 < replay_end)
        TRACER.count("commit_stream_overlap_seconds", round(overlap, 6))
        TRACER.count("commit_stream_waves_total")
        if self._exc is not None:
            raise self._exc
        return self.n_bound, None

    def abort(self) -> None:
        """Replay failed: stop the worker without raising again.  Commits
        that already landed stand (like a mid-pass sequential failure);
        chunks still queued are DROPPED — _stop makes the worker's drain
        branch skip them, so an interrupt isn't serviced through the
        whole backlog and no binds land after the wave has failed."""
        self._stop = True
        self._q.put(None)
        self._thread.join()
        for w in self._waves:
            # landed commits stand; their handles point at chunks that
            # were fully delivered before the failure
            w.seal()
        try:
            self._reflects.drain()
        # abort() runs on a wave that ALREADY failed; the replay error
        # is what surfaces — a secondary reflect error must not mask it
        # kss-analyze: allow(swallowed-exception)
        except Exception:
            pass

    # ---------------------------------------------- worker-thread side

    def _run(self) -> None:
        with TRACER.session_scope(self._session):
            while True:
                item = self._q.get()
                if item is None:
                    return
                if self._exc is not None or self._stop:
                    continue  # keep draining so finish() never blocks
                try:
                    t0 = time.perf_counter()
                    wave, lo, hi, selected = item
                    with TRACER.span("commit_stream", parent=self.parent_span,
                                     lo=lo, hi=hi):
                        self._commit(wave, lo, hi, selected)
                    self._busy.append((t0, time.perf_counter()))
                except BaseException as e:  # noqa: BLE001 — finish() re-raises
                    self._exc = e

    def _put_result(self, wave, i: int, ns: str, name: str) -> None:
        """Deposit pod i's wave result: a lazy handle (tensor-backed,
        decoded on first read) or the pre-decoded blobs."""
        if wave is not None:
            self.engine.result_store.put_lazy(ns, name, wave, i)
        else:
            self.engine.result_store.put_decoded(ns, name,
                                                 self.annotations[i])

    def attribution(self) -> dict | None:
        """Finished per-plugin attribution for the final replay run, or
        None (eager mode / broken accumulator).  Call after finish()."""
        acc = getattr(self._waves[-1], "_attr_acc", None) if self._waves \
            else None
        return acc.finish() if acc is not None else None

    def _commit(self, wave, lo: int, hi: int, selected) -> None:
        if wave is not None:
            acc = getattr(wave, "_attr_acc", None)
            if acc is not None:
                # before the watermark check: re-delivered chunks still
                # count under the NEW run's accumulator (add_chunk never
                # raises — broken accumulators just stop tallying)
                acc.add_chunk(lo // wave.chunk)
        if hi <= self._upto:
            return  # width-tier re-delivery of an already-committed chunk
        if self.gang is not None:
            self._selected[lo:hi] = selected
            if self._pod_wave is not None:
                self._pod_wave[lo:hi] = [wave] * (hi - lo)
            cut = self._gang_cut(hi)
            if cut > self._upto:
                self._commit_gang_range(self._upto, cut)
                self._upto = cut
            return
        eng = self.engine
        names = self.node_names
        items: list[tuple[str, str, str | None]] = []
        uids: list[str | None] = []
        for i in range(max(lo, self._upto), hi):
            meta = self.pending[i].get("metadata") or {}
            ns, name = meta.get("namespace") or "default", meta.get("name", "")
            self._put_result(wave, i, ns, name)
            sel = int(selected[i - lo])
            items.append((ns, name, names[sel] if sel >= 0 else None))
            uids.append(meta.get("uid"))
        self.n_bound += eng._commit_pod_batch(items)
        for (ns, name, _node), uid in zip(items, uids):
            self._reflects.submit(ns, name, uid)
        self._upto = hi

    def _gang_cut(self, hi: int) -> int:
        """Largest commit boundary <= hi that splits no gang: when the
        pods on either side of hi share a group (gangs are contiguous
        in pending order), pull the cut back to the group's first
        index so the straddling gang commits whole with the next
        chunk."""
        gid = self.gang.gid
        if hi >= len(self.pending):
            return len(self.pending)
        g = int(gid[hi])
        if g >= 0 and gid[hi - 1] == g:
            return int(self.gang.start[g])
        return hi

    def _commit_gang_range(self, lo: int, hi: int) -> None:
        """Gang-atomic commit of pending[lo:hi) (every gang inside is
        whole): the vectorized quorum pass decides allow/park per
        group; admitted members bind in pod order (parked siblings
        released right after the group's last wave member), below-
        quorum members park instead of binding — the same ordering
        rules as the sequential post-pass, so the parity gate holds."""
        eng = self.engine
        gang = self.gang
        names = self.node_names
        admit, wait_mask = eng._gang_decide(gang, self._selected, lo, hi)
        items: list[tuple[str, str, str | None]] = []
        uids: list[str | None] = []
        for i in range(lo, hi):
            meta = self.pending[i].get("metadata") or {}
            ns, name = meta.get("namespace") or "default", meta.get("name", "")
            self._put_result(
                self._pod_wave[i] if self._pod_wave is not None else None,
                i, ns, name)
            sel = int(self._selected[i])
            g = int(gang.gid[i])
            parked = False
            if g >= 0 and sel >= 0:
                if admit[g]:
                    eng._gang_record_permit(gang, ns, name, g,
                                            waited=bool(wait_mask[i - lo]))
                else:
                    eng._gang_park(gang, self.pending[i], g, names[sel])
                    parked = True
            if not parked:
                items.append((ns, name, names[sel] if sel >= 0 else None))
                uids.append(meta.get("uid"))
            if g >= 0 and i == int(gang.last[g]) and admit[g]:
                for rec in eng._gang_take_parked(gang.keys[g]):
                    items.append((rec.ns, rec.name, rec.node))
                    uids.append(rec.uid)
        self.n_bound += eng._commit_pod_batch(items)
        for (ns, name, _node), uid in zip(items, uids):
            self._reflects.submit(ns, name, uid)


class SchedulerEngine:
    def __init__(self, store: ObjectStore, reflector: StoreReflector | None = None,
                 result_store: ResultStore | None = None,
                 plugin_config: PluginSetConfig | None = None,
                 chunk: int = 512, mesh=None, unroll: int = 2,
                 pipeline_commit: bool = True):
        self.store = store
        # chunk-pipelined commit (docs/wave-pipeline.md): commit each
        # decoded chunk on a worker thread while the device scans later
        # chunks.  False forces the sequential post-pass on every wave
        # (the parity baseline, and the path the conflict-retry tests pin)
        self.pipeline_commit = pipeline_commit
        # per-wave node count for the unschedulable condition message
        # (was a full deepcopy store.list per unschedulable pod)
        self._wave_node_count: int | None = None
        self._pending_idx = None
        self.result_store = result_store or ResultStore()
        self.reflector = reflector or StoreReflector(store)
        if RESULT_STORE_KEY not in self.reflector.result_stores:
            self.reflector.add_result_store(self.result_store, RESULT_STORE_KEY)
        self.plugin_config = plugin_config or PluginSetConfig()
        self.chunk = chunk
        # lax.scan unroll for replay waves: the step's [N] ops are tiny,
        # so per-iteration overhead matters (bench.py --unroll default)
        self.unroll = unroll
        # optional jax.sharding.Mesh with a "nodes" axis: every batched
        # replay shards the node axis across it (parallel/mesh.py)
        self.mesh = mesh
        self.extender_service = None
        # plugin name -> PluginExtender (the reference's WithPluginExtenders
        # registry); a bare list is accepted as anonymous after_cycle
        # observers for backward compatibility
        self.plugin_extenders: dict | list = {}
        self.profiles: dict[str, PluginSetConfig] | None = None
        # pods parked by Permit "wait" (upstream waitingPods map analogue),
        # keyed (namespace, name); external threads may allow()/reject()
        self.waiting_pods: dict[tuple[str, str], "WaitingPod"] = {}
        # gang scheduling (docs/gang-scheduling.md): members parked by
        # the vectorized quorum pass, keyed (ns, name); each also holds
        # a WaitingPod handle in waiting_pods so pending_pods skips it.
        # Resolution is quorum completion (a later wave binds the gang
        # at the assumed nodes), scheduleTimeoutSeconds expiry (the
        # whole gang rejects), or a PodGroup update (reconciled at the
        # next schedule_pending)
        self.gang_parked: dict[tuple[str, str], _GangParked] = {}
        self._gang_wave: _GangCtx | None = None  # vectorized-mode wave ctx
        self._gang_dir = None                    # per-wave GangDirectory
        self._gang_seq = 0                       # park FIFO order
        # async waiter bookkeeping: one daemon thread per parked pod
        # finishes its binding cycle on resolution (upstream's binding
        # cycle goroutine blocking in WaitOnPermit)
        import threading

        self._wait_threads: list = []
        self._waiter_lock = threading.Lock()
        self._waiter_results: list[tuple[str, str, str]] = []
        # injectable for tests (forced-conflict soak asserts the backoff
        # schedule without waiting out real 100ms x 3^n sleeps)
        self._retry_sleep = time.sleep
        # wave failure protocol (docs/fault-injection.md): the engine's
        # own degradation-ladder level ON TOP of the env floor
        # (KSS_TPU_HOST_RESIDENT/KSS_TPU_EAGER_DECODE) — 0 device,
        # 1 host, 2 eager — and the consecutive-good-waves counter
        # driving probe-based recovery back up the ladder
        self._residency = 0
        self._resid_ok_waves = 0
        # multi-session serving (server/sessions.py): the owning
        # session's id, or None for direct engine use.  schedule_pending
        # and the engine's worker threads enter this session's tracer
        # scope, so every span/counter the wave records carries the
        # session label and the device-result budget attributes retained
        # chunks to the right per-session share
        self.session: str | None = None

    def set_plugin_config(self, cfg: PluginSetConfig) -> None:
        """Legacy single-profile API: one plugin set for every pod.
        Clears any profile routing so the new config actually takes
        effect (set_profiles is the multi-profile entry)."""
        self.plugin_config = PluginSetConfig(
            enabled=list(cfg.enabled), weights=dict(cfg.weights),
            custom=dict(cfg.custom), args=copy.deepcopy(cfg.args),
            point_enabled={k: list(v) for k, v in cfg.point_enabled.items()},
            point_disabled={k: set(v) for k, v in cfg.point_disabled.items()},
        )
        self.profiles = None

    def set_profiles(self, profiles: dict[str, PluginSetConfig] | None) -> None:
        """Multi-profile routing: one PluginSetConfig per schedulerName,
        config order preserved (upstream builds one framework per profile,
        scheduler.go:141-173).  None disables routing — every pending pod
        is scheduled with plugin_config (direct-engine / test use)."""
        if profiles:
            self.profiles = {
                n: PluginSetConfig(
                    enabled=list(c.enabled), weights=dict(c.weights),
                    custom=dict(c.custom), args=copy.deepcopy(c.args),
                    point_enabled={k: list(v)
                                   for k, v in c.point_enabled.items()},
                    point_disabled={k: set(v)
                                    for k, v in c.point_disabled.items()})
                for n, c in profiles.items()
            }
            # keep the legacy single-profile accessor pointing at the first
            self.plugin_config = next(iter(self.profiles.values()))
        else:
            self.profiles = None

    def set_extenders(self, extender_service) -> None:
        """Configure webhook extenders; scheduling switches to the phased
        (host-interleaved) path while any are present."""
        self.extender_service = extender_service
        if extender_service is not None:
            self.reflector.add_result_store(extender_service.result_store, EXTENDER_STORE_KEY)
        else:
            self.reflector.result_stores.pop(EXTENDER_STORE_KEY, None)

    # ------------------------------------------------------------ hooks

    def _extenders_map(self) -> dict:
        pe = self.plugin_extenders
        if isinstance(pe, dict):
            return pe
        return {f"_observer{i}": e for i, e in enumerate(pe or [])}

    def _cycle_hooks(self) -> dict:
        """Extenders whose plugin is enabled and that intercept the
        filter/score/normalize points — these force the host path."""
        from ..scheduler.debuggable import intercepts_cycle

        enabled = set(self.plugin_config.enabled)
        return {
            name: ext for name, ext in self._extenders_map().items()
            if name in enabled and intercepts_cycle(ext)
        }

    def _needs_host_path(self) -> bool:
        if self.extender_service is not None and self.extender_service.extenders:
            return True
        cfg = self.plugin_config
        for name in cfg.enabled:
            if cfg.is_custom(name) and getattr(cfg.custom[name], "has_normalize", False):
                return True
        return bool(self._cycle_hooks())

    # ------------------------------------------------------------ run

    def _drain_waiters(self) -> tuple[int, set[tuple[str, str]]]:
        """Join all Permit waiter threads; -> (#bound, rejected keys)."""
        while True:
            with self._waiter_lock:
                threads, self._wait_threads = self._wait_threads, []
            if not threads:
                break
            for t in threads:
                t.join()
        with self._waiter_lock:
            results, self._waiter_results = self._waiter_results, []
        bound = sum(1 for kind, _, _ in results if kind == "bound")
        rejected = {(ns, name) for kind, ns, name in results if kind == "rejected"}
        return bound, rejected

    def _list_shared(self, resource: str) -> list[dict]:
        """Read-only listing without per-object deep copies (the store's
        informer-cache contract); falls back for stores without the fast
        path (e.g. the remote HTTP cluster client)."""
        from ..cluster.store import list_shared

        return list_shared(self.store, resource)

    def pending_pods(self) -> list[dict]:
        """Unscheduled pods in queue order: a custom QueueSort plugin's
        less() when one is enabled (upstream allows exactly one,
        wrappedplugin.go:754-771), else PrioritySort.

        PrioritySort order comes from the incremental pending index when
        the store supports it (framework/pending.py: O(events) per wave
        instead of re-listing and re-sorting every pod); a custom
        QueueSort or an index-less store (the remote HTTP client) takes
        the legacy list+sort path.

        Returns SHARED store manifests (the informer-cache contract) —
        callers must not mutate them; take a deepcopy before handing one
        to anything that might."""
        qs = self._queue_sort_plugin()
        if qs is None:
            idx = self._pending_index()
            if idx is not None:
                if not self.waiting_pods:
                    return idx.pending()
                waiting = self.waiting_pods
                from .pending import _key

                return [p for p in idx.pending() if _key(p) not in waiting]
        elif self._pending_idx is not None:
            # a custom QueueSort bypasses the index permanently: drop the
            # subscription so every store write stops paying the fan-out
            # tax into a queue nothing will ever drain
            self._pending_idx.close()
            self._pending_idx = None
        pods = self._list_shared("pods")
        unbound = [
            p for p in pods if not ((p.get("spec") or {}).get("nodeName"))
        ]
        if qs is not None:
            pending = [
                p for p in unbound
                if ((p.get("metadata") or {}).get("namespace") or "default",
                    (p.get("metadata") or {}).get("name", ""))
                not in self.waiting_pods
            ]
            pending.sort(key=functools.cmp_to_key(
                lambda a, b: -1 if qs.less(a, b) else (1 if qs.less(b, a) else 0)))
            return pending
        # PrioritySort with gang-contiguous grouping: the SAME composite
        # key the incremental index orders by, so the two paths cannot
        # drift (group min keys count parked members, hence the
        # unfiltered unbound list)
        from .pending import gang_sorted

        return gang_sorted(unbound, skip=self.waiting_pods)

    def close(self) -> None:
        """Release engine-held resources: the pending index's watch
        subscription and the reflect pool.  Engines are long-lived in
        the simulator (the service reconfigures in place), but an
        application that discards an engine while its store lives on
        must call this — otherwise every subsequent store write keeps
        feeding the orphaned index queue.  The engine lazily re-creates
        both if used again."""
        if self._pending_idx is not None:
            self._pending_idx.close()
            self._pending_idx = None
        pool = getattr(self, "_reflect_pool", None)
        if pool is not None:
            pool.shutdown(wait=True)
            self._reflect_pool = None

    def _pending_index(self):
        """Lazily built PendingPodIndex, or None when the store has no
        atomic list_and_watch surface (remote HTTP client)."""
        idx = self._pending_idx
        if idx is None:
            if not hasattr(self.store, "list_and_watch"):
                return None
            from .pending import PendingPodIndex

            idx = PendingPodIndex(self.store)
            self._pending_idx = idx
        return idx

    def _queue_sort_plugin(self):
        """The enabled custom QueueSort plugin, if any.  Upstream allows
        exactly one QueueSort across ALL profiles (the scheduler refuses
        to start otherwise) — a config with two distinct queue-sort
        plugins is rejected here the same way."""
        cfgs = ([self.plugin_config] if not self.profiles
                else list(self.profiles.values()))
        found: dict[str, object] = {}
        for cfg in cfgs:
            for name in cfg.enabled:
                if cfg.is_custom(name):
                    p = cfg.custom[name]
                    if getattr(p, "has_queue_sort", False):
                        found[name] = p
        if len(found) > 1:
            raise ValueError(
                "only one QueueSort plugin can be enabled across profiles, "
                f"got {sorted(found)}")
        return next(iter(found.values()), None)

    def schedule_pending(self) -> int:
        """One scheduling wave over all pending pods (plus retry waves for
        pods unblocked by preemption, and re-runs after a custom
        Reserve/Permit/PreBind rejected a speculative placement). Returns
        #bound.  Runs under the owning session's tracer scope (self.session;
        a no-op for direct engine use).

        Pods parked by Permit "wait" do NOT stall the wave: their binding
        cycle finishes on a waiter thread when allowed/rejected/timed out
        (upstream runs binding cycles as goroutines), and this call drains
        all waiters before returning so the result is settled.

        Gang members parked by the vectorized quorum pass are the
        exception: they hold NO thread and survive across calls (their
        gang may complete in a later call's wave); expired ones are
        timeout-rejected — whole gangs at a time — at the top of every
        call (docs/gang-scheduling.md)."""
        # trace correlation (docs/metrics.md): the wave that drains the
        # submitted work claims the session's pending trace id (noted by
        # the server per workload-submitting request, consume-once) so
        # every span/event below — wave, speculative rounds, fused
        # dispatch — carries the id of the HTTP request that caused it.
        # trace_scope(None) is a no-op, so direct engine use under an
        # explicit caller-provided trace scope is left untouched.
        with TRACER.session_scope(self.session), \
                TRACER.trace_scope(TRACER.claim_session_trace(self.session)):
            return self._schedule_pending_scoped()

    def _schedule_pending_scoped(self) -> int:
        n_bound = self._gang_maintain()
        if n_bound:
            TRACER.count("pods_scheduled_total", n_bound)
        rejected: set[tuple[str, str]] = set()
        max_waves = 8 + len(self.pending_pods())
        for _ in range(max_waves):
            bound, retry = self._schedule_wave(exclude=rejected)
            n_bound += bound
            TRACER.count("pods_scheduled_total", bound)
            TRACER.count("scheduling_waves_total")
            if retry == "preempted":
                TRACER.count("preemption_waves_total")
            # drain Permit waiters after EVERY wave (not just the last):
            # a retry wave must never observe a half-resolved waiter —
            # pending_pods would re-schedule a pod whose waiter thread is
            # mid-bind
            waiter_bound, waiter_rejected = self._drain_waiters()
            n_bound += waiter_bound
            TRACER.count("pods_scheduled_total", waiter_bound)
            if waiter_rejected:
                # like a sync lifecycle rejection: re-run without them
                # (they keep their recorded rejection; upstream would
                # re-queue, which the next schedule_pending call does)
                rejected |= waiter_rejected
                continue
            if not retry:
                break
        # count unschedulable once per pass, not per retry wave (pods
        # routed to no profile are not ours to count)
        TRACER.count("pods_unschedulable_total", len([
            p for p in self.pending_pods() if self._profile_of(p) is not None
        ]))
        return n_bound

    def _profile_of(self, pod: dict) -> str | None:
        """Route a pod to a profile by spec.schedulerName (upstream
        frameworkForPod).  An unset name maps to "default-scheduler", or
        to the first profile when no profile carries that name; an
        explicit name matching no profile returns None — the pod is left
        alone, exactly as a cluster whose schedulers don't include that
        name would."""
        name = (pod.get("spec") or {}).get("schedulerName")
        if self.profiles is None:
            return "*"
        if name is None:
            if DEFAULT_SCHEDULER_NAME in self.profiles:
                return DEFAULT_SCHEDULER_NAME
            return next(iter(self.profiles))
        return name if name in self.profiles else None

    def _schedule_wave(self, exclude: set[tuple[str, str]] | None = None
                       ) -> tuple[int, str | None]:
        """One scheduling wave: each profile schedules its own pods in
        config order (binds from earlier profiles are visible to later
        ones through the store). Returns (#bound, retry reason or None)."""
        if self.profiles is None:
            return self._profile_wave(self.pending_pods(), exclude)
        # preserve GLOBAL queue order across profiles (upstream pops one
        # shared activeQ): batch maximal runs of consecutive same-profile
        # pods so a high-priority pod of profile B is never beaten to
        # capacity by a lower-priority pod of profile A
        runs: list[tuple[str, list[dict]]] = []
        for p in self.pending_pods():
            pname = self._profile_of(p)
            if pname is None:
                continue
            if runs and runs[-1][0] == pname:
                runs[-1][1].append(p)
            else:
                runs.append((pname, [p]))
        total, retry = 0, None
        for pname, mine in runs:
            saved = self.plugin_config
            self.plugin_config = self.profiles[pname]
            try:
                bound, r = self._profile_wave(mine, exclude)
            finally:
                self.plugin_config = saved
            total += bound
            retry = retry or r
        return total, retry

    def _profile_wave(self, pending: list[dict],
                      exclude: set[tuple[str, str]] | None = None
                      ) -> tuple[int, str | None]:
        """Timed shell around _profile_wave_run: feeds the upstream-named
        scheduling_attempt_duration_seconds histogram — wave wall
        amortized per pod (the batched paths have no per-pod attempt
        clock), result=scheduled for bound pods, unschedulable for the
        rest of the wave (an approximation: parked gang members and
        gated pods count as unschedulable until they resolve)."""
        t0 = time.perf_counter()
        bound, retry = self._profile_wave_run(pending, exclude)
        n = len(pending)
        if n:
            # per-session SLO window (rolling p50/p99 wave latency +
            # cycles/s): one deque append, read by /api/v1/sessions and
            # /readyz (utils/blackbox.py, docs/metrics.md)
            from ..utils.blackbox import SLO

            SLO.observe_wave(self.session, time.perf_counter() - t0, n)
            per = (time.perf_counter() - t0) / n
            if bound:
                TRACER.observe("scheduling_attempt_duration_seconds", per,
                               n=bound, result="scheduled")
            if n > bound:
                TRACER.observe("scheduling_attempt_duration_seconds", per,
                               n=n - bound, result="unschedulable")
        return bound, retry

    # ------------------------------------------------ failure protocol

    @staticmethod
    def _env_int(name: str, default: int) -> int:
        from ..utils.env import env_int

        return env_int(name, default)

    @staticmethod
    def _env_residency_floor() -> int:
        """The ladder level the environment pins as a floor: the engine
        may degrade BELOW it but never recovers above it."""
        if os.environ.get("KSS_TPU_EAGER_DECODE") == "1":
            return 2
        if os.environ.get("KSS_TPU_HOST_RESIDENT") == "1":
            return 1
        return 0

    def _effective_residency(self) -> int:
        return max(self._env_residency_floor(), self._residency)

    def result_mode(self) -> str:
        """The wave's current result-residency rung (device_resident /
        host_resident / eager_decode) — surfaced per session on
        /api/v1/sessions and /readyz (docs/fault-injection.md)."""
        return _RESIDENCY_MODES[self._effective_residency()]

    def _degrade(self, seam: str) -> bool:
        """Step one rung down the ladder after a structural device
        fault.  False when already at the bottom (eager decode has no
        device dependency left to shed)."""
        cur = self._effective_residency()
        if cur >= len(_RESIDENCY_MODES) - 1:
            return False
        self._residency = cur + 1
        self._resid_ok_waves = 0
        TRACER.inc("wave_faults_total", seam=seam, action="degraded")
        TRACER.inc("wave_degradations_total",
                   **{"from": _RESIDENCY_MODES[cur],
                      "to": _RESIDENCY_MODES[cur + 1]})
        from ..utils.blackbox import BLACKBOX

        BLACKBOX.record("degrade", seam=seam,
                        from_mode=_RESIDENCY_MODES[cur],
                        to_mode=_RESIDENCY_MODES[cur + 1])
        # a degradation is a structural event worth a post-mortem even
        # though the wave survives: snapshot the ring (in memory; wave
        # ABORTS additionally write to KSS_TPU_BLACKBOX_DIR)
        BLACKBOX.dump("degradation", session=self.session)
        return True

    def _wave_recovered_ok(self) -> None:
        """Probe-based recovery: after KSS_TPU_DEGRADE_PROBE_WAVES
        consecutive clean waves at a degraded rung, step back UP one
        level (never above the env floor).  The next wave is the probe:
        if it faults structurally again, _degrade steps straight back
        down and the counter restarts."""
        if self._residency <= 0:
            return
        floor = self._env_residency_floor()
        cur = self._effective_residency()
        if cur <= floor:
            self._residency = 0  # env already enforces this rung
            return
        self._resid_ok_waves += 1
        if self._resid_ok_waves < self._env_int(
                "KSS_TPU_DEGRADE_PROBE_WAVES", 8):
            return
        self._resid_ok_waves = 0
        new = max(cur - 1, floor)
        self._residency = 0 if new <= floor else new
        TRACER.inc("wave_degradations_total",
                   **{"from": _RESIDENCY_MODES[cur],
                      "to": _RESIDENCY_MODES[new]})
        from ..utils.blackbox import BLACKBOX

        BLACKBOX.record("recover", from_mode=_RESIDENCY_MODES[cur],
                        to_mode=_RESIDENCY_MODES[new])

    def _profile_wave_run(self, pending: list[dict],
                          exclude: set[tuple[str, str]] | None = None
                          ) -> tuple[int, str | None]:
        """The wave failure protocol (docs/fault-injection.md) around
        _profile_wave_attempt: classify a mid-wave fault and

          * transient  — retry the UNCOMMITTED SUFFIX with bounded
            backoff (KSS_TPU_WAVE_MAX_RETRIES, default 3): committed
            chunks stand (their binds/parks landed through the gang-cut
            watermark, so gang atomicity holds at the boundary), the
            suffix recompiles against current store state — the same
            recompile-with-upstream-state mechanism the "rejected"
            retry path already parity-proves — and bind order stays
            deterministic;
          * structural — step the residency ladder down one rung
            (device -> host -> eager; all bit-identical parity gates)
            and re-run, with probe-based recovery stepping back up
            after consecutive clean waves;
          * fatal      — surface immediately (interrupts, exhausted
            bounded retries, quarantined compiles).

        With no fault the attempt's result passes straight through —
        the try block is the only overhead on the happy path."""
        from ..utils.blackbox import BLACKBOX
        from ..utils.faults import classify_fault
        from .replay import (CompileQuarantined, materialize_failure_streak,
                             reset_materialize_failures)

        # black-box wave marker: records the event AND pins the counter
        # baseline this wave's post-mortem computes deltas against
        BLACKBOX.wave_start(self.session, pods=len(pending),
                            mode=self.result_mode())
        if (self._effective_residency() == 0
                and materialize_failure_streak(self.session)
                >= self._env_int("KSS_TPU_MATERIALIZE_FAIL_LIMIT", 3)):
            # repeated on-demand D2H failures are a structural device
            # signal even though they surface on the READ path: step to
            # host-resident fetch so new waves stop pinning chunks that
            # cannot come back across.  The streak is per-session: a
            # neighbor's flaky reads never degrade THIS engine
            if self._degrade("replay.materialize"):
                reset_materialize_failures(self.session)
        bound = 0
        retries_left = self._env_int("KSS_TPU_WAVE_MAX_RETRIES", 3)
        delay = 0.02
        while True:
            try:
                b, retry = self._profile_wave_attempt(pending, exclude)
            except _WaveAbort as ab:
                bound += ab.n_bound
                pending = ab.remaining
                cause = ab.cause
                seam = getattr(cause, "seam", None) or ab.stage
                kind = classify_fault(cause)
                BLACKBOX.record("wave.fault", stage=ab.stage, seam=seam,
                                error=type(cause).__name__,
                                classification=kind, bound=ab.n_bound,
                                remaining=len(pending))
                if isinstance(cause, CompileQuarantined):
                    # per-key containment already happened in the scan
                    # cache; retrying here would only re-read the
                    # quarantine — surface it to the caller/session
                    BLACKBOX.record("wave.abort", seam=seam,
                                    action="quarantined")
                    BLACKBOX.dump("wave_abort", cause=cause,
                                  session=self.session, write=True)
                    raise cause
                if kind == "structural":
                    if self._degrade(seam):
                        continue
                    TRACER.inc("wave_faults_total", seam=seam,
                               action="aborted")
                    BLACKBOX.record("wave.abort", seam=seam,
                                    action="aborted")
                    BLACKBOX.dump("wave_abort", cause=cause,
                                  session=self.session, write=True)
                    raise cause
                if kind == "transient" and retries_left > 0:
                    # retry even with an EMPTY suffix: every pod already
                    # committed, so the fault hit post-commit work (e.g.
                    # a reflect drain — its records stay queued and land
                    # on the next read/reflect); the empty re-attempt
                    # settles immediately and the wave returns its bind
                    # count instead of crashing a fully-committed wave
                    retries_left -= 1
                    TRACER.count("wave_retries_total")
                    TRACER.inc("wave_faults_total", seam=seam,
                               action="retried")
                    BLACKBOX.record("wave.retry", seam=seam,
                                    remaining=len(pending),
                                    retries_left=retries_left)
                    self._retry_sleep(delay)
                    delay = min(delay * 5, 1.0)
                    continue
                TRACER.inc("wave_faults_total", seam=seam, action="aborted")
                BLACKBOX.record("wave.abort", seam=seam, action="aborted")
                # a failed wave ships its own evidence: the bundle is
                # auto-written to KSS_TPU_BLACKBOX_DIR when set
                # (docs/fault-injection.md)
                BLACKBOX.dump("wave_abort", cause=cause,
                              session=self.session, write=True)
                raise cause
            self._wave_recovered_ok()
            BLACKBOX.record("wave.end", bound=bound + b,
                            retry=retry or None)
            return bound + b, retry

    def _guarded_replay(self, stage: str, pending: list, fn):
        """Run one replay under the failure protocol's classification:
        nothing was committed yet on these paths (the sequential/
        speculative commits happen in _finish_wave AFTER the replay
        drains), so a fault retries the whole FILTERED pending list —
        retrying the filtered list (not the caller's raw one) keeps
        gate marks and gang-prescreen rejections single-shot."""
        try:
            return fn()
        except BaseException as e:
            raise _WaveAbort(e, pending, 0, stage) from e

    def _profile_wave_attempt(self, pending: list[dict],
                              exclude: set[tuple[str, str]] | None = None
                              ) -> tuple[int, str | None]:
        """One wave over the given pending pods with the current
        plugin_config. Returns (#bound, retry reason or None).

        retry == "preempted": preemption nominated a node, run a retry wave.
        retry == "rejected": a custom Reserve/Permit/PreBind rejected a pod
        AFTER the device replay speculatively folded it into the carry —
        the rest of the wave is re-run with upstream-sequential state (the
        rejected pod excluded), so later pods never observe the phantom
        bind (upstream scheduleOne semantics)."""
        if exclude:
            pending = [
                p for p in pending
                if ((p.get("metadata") or {}).get("namespace") or "default",
                    (p.get("metadata") or {}).get("name", "")) not in exclude
            ]
        if self.plugin_config.preenqueues():
            # SchedulingGates PreEnqueue: gated pods never enter the queue
            gated = [
                p for p in pending if (p.get("spec") or {}).get("schedulingGates")
            ]
            for p in gated:
                meta = p.get("metadata") or {}
                self._mark_gated(meta.get("namespace") or "default", meta.get("name", ""))
            if gated:
                pending = [
                    p for p in pending
                    if not (p.get("spec") or {}).get("schedulingGates")
                ]
        if not pending:
            return 0, None
        nodes = self._list_shared("nodes")
        self._wave_node_count = len(nodes)
        pods_all = self._list_shared("pods")
        self._gang_wave = None
        gp = self._gang_plugin()
        gang_dir = None
        if gp is not None:
            pending, gang_dir = self._gang_prescreen(pending, gp, pods_all,
                                                     nodes)
            if not pending:
                return 0, None
        bound = [
            (p, p["spec"]["nodeName"]) for p in pods_all
            if (p.get("spec") or {}).get("nodeName")
        ]
        if self.gang_parked:
            # parked gang members keep their speculative assignments as
            # assumed binds: their resources stay reserved while the
            # gang waits for quorum (docs/gang-scheduling.md)
            bound += self._gang_assumed_bound()
        # volume manifests for the VolumeBinding/Zone/Restrictions/Limits
        # family; CSINode is not one of the simulator's 7 synced GVRs
        # (reference: recorder/recorder.go:45-53), so limits come only from
        # callers using compile_workload directly
        volumes = {
            "pvcs": self._list_shared("persistentvolumeclaims"),
            "pvs": self._list_shared("persistentvolumes"),
            "storageclasses": self._list_shared("storageclasses"),
        }
        with TRACER.span("compile_workload", pods=len(pending), nodes=len(nodes)):
            from ..state.compile import NodeTableReuse

            cw = compile_workload(
                nodes, pending, self.plugin_config, bound_pods=bound,
                volumes=volumes, reuse=getattr(self, "_last_cw", None),
                namespaces=self._list_shared("namespaces"),
                # columnar pod view (when the store lists columnar):
                # request rows gather from pre-parsed bank columns
                pod_columns=getattr(pods_all, "columns", None),
            )
            self._last_cw = NodeTableReuse(cw)
        if self._needs_host_path():
            # gangs route through the per-pod Permit machinery here
            # (the Coscheduling plugin stays in the lifecycle set)
            return self._schedule_host_path(cw, pending)

        if gp is not None and self._gang_vectorized():
            # setting the wave ctx removes the gang plugin from the
            # custom-lifecycle set: the quorum pass below replaces its
            # per-pod Permit calls on both batched commit paths (the
            # falsy sentinel keeps gang-free waves on the plain code)
            ctx = (_GangCtx(gp.name, pending, gang_dir,
                            self._gang_parked_counts())
                   if gang_dir is not None else None)
            self._gang_wave = ctx if ctx else _GANG_NONE

        # a live cluster's node count need not divide the mesh's "nodes"
        # extent; shard only waves where it does and run the rest
        # unsharded (shard_workload would reject the shape) — speculative
        # dp batching below tolerates mesh=None
        mesh = self.mesh
        if mesh is not None:
            from ..parallel.mesh import can_shard

            if not can_shard(cw.n_nodes, mesh):
                TRACER.count("mesh_fallback_indivisible_nodes_total")
                mesh = None

        from ..store.decode import decode_chunk_into

        if (os.environ.get("KSS_TPU_SPECULATIVE", "1") != "0"
                and self.extender_service is None
                and not self._custom_lifecycle_plugins()):
            # speculative multi-pod rounds are the DEFAULT wave whenever
            # the active plugin set admits exact batching — a single
            # device suffices (a mesh additionally fans the batch over
            # its "dp" axis; this uses the divisibility-checked mesh).
            # KSS_TPU_SPECULATIVE=0 pins the sequential scan: the parity
            # baseline the golden suite diffs against.  The engine's
            # vectorized gang plugin is ignored by the eligibility check
            # (its PreFilter ran in the prescreen, admission happens in
            # the quorum pass at commit — it neither filters nor scores
            # on device)
            from ..parallel.speculative import speculation_ok

            ignore = (frozenset({gp.name})
                      if gp is not None and self._gang_wave is not None
                      else frozenset())
            if speculation_ok(self.plugin_config, have_manifests=True,
                              ignore=ignore):
                return self._speculative_wave(cw, mesh, pending, exclude,
                                              len(nodes), ignore)

        if self._custom_lifecycle_plugins():
            # a custom Reserve/Permit/PreBind can reject mid-wave and abort
            # the rest — decode per pod so an aborted wave wastes nothing.
            # host-resident: the lifecycle loop consumes every pod's
            # annotations in order, so deferring the D2H would just move
            # the whole transfer out of the scan-overlap window
            def _lc_replay():
                with TRACER.span("device_replay", pods=len(pending),
                                 nodes=len(nodes)) as sp:
                    rr = replay(
                        cw, chunk=min(self.chunk, max(len(pending), 1)),
                        mesh=mesh, unroll=self.unroll,
                        device_resident=False)
                return rr, sp.seconds

            rr, replay_seconds = self._guarded_replay(
                "device_replay", pending, _lc_replay)
            all_annotations = _LazyDecode(rr)
            self._record_attribution(rr, replay_seconds)
            return self._finish_wave(cw, rr, all_annotations, pending, exclude)

        if self._can_stream_commit():
            # chunk-pipelined commit (docs/wave-pipeline.md): a worker
            # thread runs the commit phase for each decoded chunk (result
            # -store puts, batched binds/unschedulable marks, reflect
            # submissions, pod order preserved) while the device scans
            # later chunks — instead of the whole wave idling through a
            # sequential post-pass after the replay drains.  In lazy
            # mode the worker consumes tensor-level decisions only and
            # the decode leaves the critical path entirely.
            committer = _WaveCommitter(self, cw.node_table.names, pending,
                                       gang=self._gang_wave,
                                       lazy=self._wave_lazy_ok())
            try:
                with TRACER.span("replay_and_decode_stream",
                                 pods=len(pending), nodes=len(nodes)) as sp:
                    # the worker's commit_stream spans parent under the
                    # wave's replay span across the thread boundary.
                    # Lazy waves keep results DEVICE-resident: on_chunk
                    # is a handoff, the commit consumes decision rows
                    # only, and the heavy tensors never cross in-wave
                    # (unless the degradation ladder stepped to host)
                    committer.parent_span = sp.id
                    rr = replay(cw, chunk=min(self.chunk, max(len(pending), 1)),
                                mesh=mesh, unroll=self.unroll,
                                on_chunk=committer.on_chunk,
                                device_resident=(
                                    committer.lazy
                                    and self._effective_residency() == 0))
            except BaseException as e:
                # abort BEFORE reading the watermark: committed chunks
                # stand (binds/parks through the last gang-cut), queued
                # chunks drop — then hand the failure protocol the
                # settled commit boundary so only the suffix retries
                committer.abort()
                raise _WaveAbort(e, pending[committer._upto:],
                                 committer.n_bound, "replay_stream") from e
            try:
                result = committer.finish()
            except BaseException as e:
                raise _WaveAbort(e, pending[committer._upto:],
                                 committer.n_bound, "commit_stream") from e
            self._record_attribution(rr, sp.seconds,
                                     att=committer.attribution())
            return result

        if self._wave_lazy_ok():
            # sequential post-pass, lazy: the replay streams only the
            # per-pod decision rows (device-resident results — no heavy
            # tensor D2H, no on_chunk decode); the commit below deposits
            # LazyWave handles and defers the reflect — first read
            # materializes D2H + decode (store/lazy.py)
            from ..store.lazy import LazyWave

            def _lazy_replay():
                with TRACER.span("replay_and_decode_stream",
                                 pods=len(pending), nodes=len(nodes)) as sp:
                    rr = replay(
                        cw, chunk=min(self.chunk, max(len(pending), 1)),
                        mesh=mesh, unroll=self.unroll,
                        device_resident=self._effective_residency() == 0)
                return rr, sp.seconds

            rr, replay_seconds = self._guarded_replay(
                "replay_stream", pending, _lazy_replay)
            self._record_attribution(rr, replay_seconds)
            return self._finish_wave(
                cw, rr, None, pending, exclude,
                lazy_wave=LazyWave(rr, len(pending), sealed=True))

        # stream: each chunk decodes (chunk-granular native call, or the
        # host thread pool on the fallback ladder) as soon as its
        # transfer lands, overlapping the device's later chunks
        all_annotations = [None] * len(pending)

        def _eager_replay():
            with TRACER.span("replay_and_decode_stream", pods=len(pending),
                             nodes=len(nodes)) as sp:
                rr = replay(
                    cw, chunk=min(self.chunk, max(len(pending), 1)),
                    mesh=mesh, unroll=self.unroll,
                    on_chunk=lambda rr_, lo, hi: decode_chunk_into(
                        rr_, lo, hi, all_annotations))
            return rr, sp.seconds

        rr, replay_seconds = self._guarded_replay(
            "replay_stream", pending, _eager_replay)
        self._record_attribution(rr, replay_seconds)
        return self._finish_wave(cw, rr, all_annotations, pending, exclude)

    def _speculative_wave(self, cw, mesh, pending,
                          exclude: set[tuple[str, str]] | None,
                          n_nodes: int, ignore: frozenset = frozenset()
                          ) -> tuple[int, str | None]:
        """The engine's default wave (docs/wave-pipeline.md
        speculative-wave stage): vmapped rounds of B queued pods against
        the frozen carry, a conflict oracle accepting the provably
        non-interfering prefix, accepted results streamed to the commit
        worker on the standard chunk grid — so lazy decode, device
        residency, the gang-cut watermark and the wave failure
        protocol's uncommitted-suffix retry all compose unchanged.  A
        contention collapse hands the wave's remainder to the
        sequential chunked scan in-stream (parallel/speculative.py)."""
        from ..parallel.speculative import replay_speculative_stream
        from ..store.decode import decode_chunk_into

        namespaces = self._list_shared("namespaces")
        gang = self._gang_wave if self._gang_wave else None
        chunk = min(self.chunk, max(len(pending), 1))
        if self._can_stream_commit():
            committer = _WaveCommitter(self, cw.node_table.names, pending,
                                       gang=gang, lazy=self._wave_lazy_ok())
            try:
                with TRACER.span("replay_and_decode_stream",
                                 pods=len(pending), nodes=n_nodes,
                                 mode="speculative") as sp:
                    committer.parent_span = sp.id
                    rr, _stats = replay_speculative_stream(
                        cw, mesh, chunk=chunk, unroll=self.unroll,
                        pods=pending, namespaces=namespaces,
                        on_chunk=committer.on_chunk,
                        device_resident=(
                            committer.lazy
                            and self._effective_residency() == 0),
                        gang=gang, ignore=ignore)
            except BaseException as e:
                # abort BEFORE reading the watermark: committed chunks
                # stand, queued chunks drop — then hand the failure
                # protocol the settled commit boundary so only the
                # suffix retries (same shape as the scan stream)
                committer.abort()
                raise _WaveAbort(e, pending[committer._upto:],
                                 committer.n_bound,
                                 "speculative_replay") from e
            try:
                result = committer.finish()
            except BaseException as e:
                raise _WaveAbort(e, pending[committer._upto:],
                                 committer.n_bound, "commit_stream") from e
            self._record_attribution(rr, sp.seconds,
                                     att=committer.attribution())
            return result
        # sequential-commit shell (pipeline_commit=False, postfilter
        # preemption, plugin-extender observers): run the stream without
        # the worker, commit through the shared post-pass.  Eager waves
        # decode chunk-by-chunk DURING the stream — the pooled chunk
        # decoder overlapped with later rounds — never one whole-wave
        # decode_chunk_into(0, P) call on the commit thread
        lazy = self._wave_lazy_ok()
        all_annotations = None
        on_chunk = None
        if not lazy:
            all_annotations = [None] * len(pending)

            def on_chunk(rr_, lo, hi):
                decode_chunk_into(rr_, lo, hi, all_annotations)

        def _spec_replay():
            with TRACER.span("replay_and_decode_stream", pods=len(pending),
                             nodes=n_nodes, mode="speculative") as sp:
                rr, _stats = replay_speculative_stream(
                    cw, mesh, chunk=chunk, unroll=self.unroll,
                    pods=pending, namespaces=namespaces, on_chunk=on_chunk,
                    device_resident=(lazy
                                     and self._effective_residency() == 0),
                    gang=gang, ignore=ignore)
            return rr, sp.seconds

        rr, spec_seconds = self._guarded_replay(
            "speculative_replay", pending, _spec_replay)
        self._record_attribution(rr, spec_seconds)
        if lazy:
            from ..store.lazy import LazyWave

            return self._finish_wave(
                cw, rr, None, pending, exclude,
                lazy_wave=LazyWave(rr, len(pending), sealed=True))
        return self._finish_wave(cw, rr, all_annotations, pending, exclude)

    def _wave_lazy_ok(self) -> bool:
        """True when this wave may defer annotation decode to first read
        (store/lazy.py): lazy is the default on the batched tensor paths
        — the commit consumes tensor-level decisions only, so decoding
        on the critical path buys nothing, and the heavy replay tensors
        stay DEVICE-resident until a cold read (framework/replay.py
        device-residency; KSS_TPU_HOST_RESIDENT=1 keeps lazy decode but
        fetches to host in-wave) — and turns off when

          * KSS_TPU_EAGER_DECODE=1 (the golden/parity baseline mode);
          * plugin-extender observers are registered (after_cycle sees
            each pod's decoded annotations during the wave);
          * the store/reflector pair cannot make deferred results
            transparent to readers (no read hooks / no batch surface —
            e.g. the remote HTTP cluster client).

        The host-interleaved and custom-lifecycle paths decode per pod
        regardless (their cycles consume annotations inline).  The
        degradation ladder's bottom rung (docs/fault-injection.md)
        forces eager decode the same way the env baseline does."""
        if os.environ.get("KSS_TPU_EAGER_DECODE") == "1":
            return False
        if self._effective_residency() >= 2:
            return False
        if self._extenders_map():
            return False
        return self.reflector.defer_supported() \
            if hasattr(self.reflector, "defer_supported") else False

    def _can_stream_commit(self) -> bool:
        """True when nothing in the configuration forces the sequential
        post-pass: no plugin-extender observers (after_cycle sees each
        pod's annotations in order), no custom lifecycle (Reserve/Permit/
        PreBind can reject and abort the wave), and no PostFilter
        (preemption mutates the store mid-commit and requests retry
        waves).  Extender webhooks already forced the host path before
        this point."""
        return (self.pipeline_commit
                and not self._extenders_map()
                and not self._custom_lifecycle_plugins()
                and not self.plugin_config.postfilters())

    def _record_attribution(self, rr, replay_seconds: float,
                            att: dict | None = None) -> None:
        """Per-plugin attribution from the replay tensors the wave
        already decoded (docs/metrics.md): labeled WORK counters (pods x
        nodes evaluated, first-fail filter rejects, raw score column
        sums over feasible nodes, prefilter screens) — fused device
        execution has no per-plugin wall clock — plus the upstream-named
        framework_extension_point / plugin_execution histograms with
        the replay span APPORTIONED across points and plugins by
        evaluated work (documented estimate; host-path plugins record
        real wall time instead).  Never fails a wave."""
        try:
            from .replay import plugin_attribution

            t0 = time.perf_counter()
            if att is None:
                # streaming lazy waves pass the worker-accumulated
                # tallies instead (ChunkAttribution); everything else
                # pays the whole-result pass here
                att = plugin_attribution(rr)
            if att is None:
                return
            work: dict[tuple[str, str], int] = {}
            for name, d in att["filter"].items():
                TRACER.inc("plugin_pods_nodes_evaluated_total", d["evaluated"],
                           plugin=name, extension_point="filter")
                TRACER.inc("plugin_filter_rejects_total", d["rejects"],
                           plugin=name)
                work[("filter", name)] = d["evaluated"]
            for name, d in att["score"].items():
                TRACER.inc("plugin_pods_nodes_evaluated_total", d["evaluated"],
                           plugin=name, extension_point="score")
                TRACER.inc("plugin_score_sum_total", d["sum"], plugin=name)
                work[("score", name)] = d["evaluated"]
            for name, d in att["prefilter"].items():
                TRACER.inc("plugin_pods_nodes_evaluated_total", d["evaluated"],
                           plugin=name, extension_point="prefilter")
                TRACER.inc("plugin_prefilter_screens_total", d["screened"],
                           plugin=name)
                work[("prefilter", name)] = d["evaluated"]
            total = sum(work.values())
            if replay_seconds > 0 and total > 0:
                points: dict[str, float] = {}
                for (point, name), w in work.items():
                    if w <= 0:
                        continue
                    share = replay_seconds * w / total
                    points[point] = points.get(point, 0.0) + share
                    TRACER.observe("plugin_execution_duration_seconds", share,
                                   plugin=name, extension_point=point,
                                   status="Success")
                for point, secs in points.items():
                    TRACER.observe(
                        "framework_extension_point_duration_seconds", secs,
                        extension_point=point)
            TRACER.count("wave_attribution_seconds",
                         round(time.perf_counter() - t0, 6))
        # kss-analyze: allow(swallowed-exception)
        except Exception:
            pass  # attribution is observability; waves never fail on it

    def _finish_wave(self, cw, rr, all_annotations, pending,
                     exclude: set[tuple[str, str]] | None,
                     lazy_wave=None) -> tuple[int, str | None]:
        """Commit + reflect phase of a wave, shared by the scan and
        speculative replay paths: result-store puts, extender hooks,
        custom lifecycle, binds, postfilter/preemption, write-backs.

        lazy_wave: a sealed LazyWave standing in for all_annotations —
        the commit deposits handles and routes write-backs through
        reflect_batch so they defer with the decode (store/lazy.py);
        callers pass it only when no hook/lifecycle consumer needs the
        decoded bytes during the wave."""
        postfilter_on = bool(self.plugin_config.postfilters())
        n_bound = 0
        retry: str | None = None
        # write-backs are independent per pod (upstream's reflector runs
        # on informer callbacks, async from scheduleOne): fan them over a
        # small pool — the native escape pass releases the GIL — and
        # settle before the wave returns.  Per-pod reflect (use_batch=
        # False) keeps this post-pass on its pre-change write mechanism;
        # lazy waves use the batch surface, whose deferral IS the point.
        reflects = _ReflectBatcher(self, len(pending),
                                   use_batch=lazy_wave is not None)

        emap = self._extenders_map()
        has_lc = bool(self._custom_lifecycle_plugins())
        gang = self._gang_wave if self._gang_wave else None
        gang_admit = gang_wait = None
        if gang is not None:
            # gang-atomic commit: one vectorized quorum pass over the
            # whole wave decides allow/park per group before any write
            gang_admit, gang_wait = self._gang_decide(
                gang, np.asarray(rr.selected, dtype=np.int32), 0,
                len(pending))
        with TRACER.span("commit_and_reflect", pods=len(pending)) as commit_sp:
            for i, pod in enumerate(pending):
                meta = pod.get("metadata") or {}
                ns, name = meta.get("namespace") or "default", meta.get("name", "")
                if lazy_wave is not None:
                    self.result_store.put_lazy(ns, name, lazy_wave, i)
                else:
                    annotations = all_annotations[i]
                    self.result_store.put_decoded(ns, name, annotations)
                # one private copy serves every third-party surface this
                # cycle (hooks and plugins must not reach shared manifests)
                priv = copy.deepcopy(pod) if emap or has_lc else pod
                if emap:
                    # extender observers force eager waves (_wave_lazy_ok)
                    for hook in emap.values():
                        hook.after_cycle(priv, annotations, self.result_store)
                sel = int(rr.selected[i])
                g = int(gang.gid[i]) if gang is not None else -1
                if g >= 0 and sel >= 0:
                    if gang_admit[g]:
                        self._gang_record_permit(gang, ns, name, g,
                                                 waited=bool(gang_wait[i]))
                    else:
                        # below quorum: the speculative assignment rolls
                        # back to waiting — no bind, no status write, no
                        # reflect until the gang resolves
                        self._gang_park(gang, pod, g,
                                        cw.node_table.names[sel])
                        continue
                if sel >= 0:
                    lc = self._run_custom_lifecycle(
                        priv, ns, name, cw.node_table.names[sel],
                        allow_async=True, private=True)
                    if lc == "deferred":
                        # Permit "wait" parked the pod; its waiter thread
                        # finishes the binding cycle + reflect.  The carry
                        # already holds the speculative bind — exactly the
                        # assumed-pod state upstream exposes while a pod
                        # waits in WaitOnPermit — so the wave continues
                        continue
                    if not lc:
                        # a custom Reserve/Permit/PreBind rejected, but the
                        # device replay already folded this pod into the
                        # carry; abandon the rest of the wave and re-run it
                        # without this pod so later pods see true (unbound)
                        # state
                        self._mark_unschedulable(ns, name)
                        reflects.drain()
                        self.reflector.reflect(ns, name, uid=meta.get("uid"))
                        if exclude is not None:
                            exclude.add((ns, name))
                        return n_bound, "rejected"
                    self._bind(ns, name, cw.node_table.names[sel])
                    self._run_custom_postbind(priv, cw.node_table.names[sel],
                                              private=True)
                    n_bound += 1
                else:
                    # PreFilter-rejected pods skip preemption: the static
                    # rejects are UnschedulableAndUnresolvable upstream, and
                    # ReadWriteOncePod preemption (preempting the PVC holder)
                    # is not modeled — documented divergence
                    if postfilter_on and int(rr.prefilter_reject[i]) == 0:
                        if self._run_postfilter(
                                cw, rr.codes_of(i), i, pod, ns, name):
                            retry = "preempted"
                    self._mark_unschedulable(ns, name)
                reflects.submit(ns, name, meta.get("uid"))
                if g >= 0 and i == int(gang.last[g]) and gang_admit[g]:
                    # the group's last wave member landed: release its
                    # parked members (earlier waves) at their assumed
                    # nodes, in park order — the same position the
                    # streaming committer releases them at
                    for rec in self._gang_take_parked(gang.keys[g]):
                        self._bind(rec.ns, rec.name, rec.node)
                        n_bound += 1
                        reflects.submit(rec.ns, rec.name, rec.uid)
            reflects.drain()
        TRACER.observe("framework_extension_point_duration_seconds",
                       commit_sp.seconds, extension_point="bind")
        return n_bound, retry

    def _reflector_pool(self):
        """Lazily created pool for the per-pod write-backs."""
        pool = getattr(self, "_reflect_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(max_workers=4,
                                      thread_name_prefix="reflect")
            self._reflect_pool = pool
        return pool

    def _custom_lifecycle_plugins(self) -> list:
        plugins = [
            p for n, p in self.plugin_config.custom.items()
            if n in self.plugin_config.enabled and getattr(p, "has_lifecycle", False)
        ]
        if self._gang_wave is not None:
            # the vectorized quorum pass replaces the gang plugin's
            # per-pod Permit calls for this wave (docs/gang-scheduling.md)
            plugins = [p for p in plugins
                       if not getattr(p, "is_gang_plugin", False)]
        return plugins

    # ------------------------------------------------------------ gangs

    def _gang_plugin(self):
        """The enabled gang-admission (Coscheduling) plugin, attached to
        this engine, or None."""
        cfg = self.plugin_config
        for n in cfg.enabled:
            if n in cfg.custom:
                p = cfg.custom[n]
                if getattr(p, "is_gang_plugin", False):
                    attach = getattr(p, "attach", None)
                    if attach is not None and getattr(p, "_engine", None) is not self:
                        attach(self)
                    return p
        return None

    def _gang_vectorized(self) -> bool:
        """True when gang admission can use the vectorized quorum pass:
        the gang plugin is the ONLY enabled custom lifecycle plugin and
        the queue keeps the default PrioritySort order.  Any other
        lifecycle plugin — or a custom QueueSort, whose arbitrary
        less() defeats the gang-contiguity invariant the pass and the
        streaming cuts rely on — routes gangs through the per-pod
        Permit machinery instead (fallback matrix in
        docs/gang-scheduling.md)."""
        cfg = self.plugin_config
        for n, p in cfg.custom.items():
            if (n in cfg.enabled and getattr(p, "has_lifecycle", False)
                    and not getattr(p, "is_gang_plugin", False)):
                return False
        try:
            return self._queue_sort_plugin() is None
        except ValueError:
            return False  # invalid multi-QueueSort config: stay safe

    def _gang_parked_counts(self) -> dict[tuple[str, str], int]:
        counts: dict[tuple[str, str], int] = {}
        for rec in self.gang_parked.values():
            counts[rec.group] = counts.get(rec.group, 0) + 1
        return counts

    def _gang_assumed_bound(self) -> list[tuple[dict, str]]:
        """Parked members' speculative assignments as assumed binds for
        compile_workload's bound_pods: their resources stay reserved
        while the gang waits for quorum — the upstream assumed-pod
        state a WaitOnPermit parker holds in the scheduler cache."""
        out: list[tuple[dict, str]] = []
        for (ns, name), rec in list(self.gang_parked.items()):
            try:
                pod = self.store.get("pods", name, ns, copy_object=False)
            # a parked pod deleted from the store stops reserving capacity
            # kss-analyze: allow(swallowed-exception)
            except NotFound:
                continue
            except TypeError:  # store without the no-copy fast path
                try:
                    pod = self.store.get("pods", name, ns)
                # kss-analyze: allow(swallowed-exception) — as above
                except NotFound:
                    continue
            out.append((pod, rec.node))
        return out

    def _gang_take_parked(self, group_key: tuple[str, str]) -> list[_GangParked]:
        """Pop every parked member of group_key in park (FIFO) order."""
        recs = [r for r in self.gang_parked.values() if r.group == group_key]
        recs.sort(key=lambda r: r.seq)
        for r in recs:
            self.gang_parked.pop((r.ns, r.name), None)
            self.waiting_pods.pop((r.ns, r.name), None)
        return recs

    def _gang_park(self, ctx: _GangCtx, pod: dict, g: int, node: str) -> None:
        """Roll a below-quorum member's speculative assignment back to
        waiting: permit-result "wait" is recorded (reflected at
        resolution), the pod parks in waiting_pods (so pending_pods
        skips it) and gang_parked keeps the assumed node + deadline.
        No store write happens until the gang resolves."""
        from .waiting import WaitingPod

        meta = pod.get("metadata") or {}
        ns, name = meta.get("namespace") or "default", meta.get("name", "")
        self.result_store.add_permit_result(
            ns, name, ctx.gp_name, ann.WAIT_MESSAGE, ctx.timeout_str[g])
        key = (ns, name)
        self.waiting_pods[key] = WaitingPod(pod, {ctx.gp_name: ctx.timeout_s[g]})
        self._gang_seq += 1
        self.gang_parked[key] = _GangParked(
            ns, name, meta.get("uid"), node, ctx.keys[g],
            deadline=time.monotonic() + ctx.timeout_s[g],
            timeout_str=ctx.timeout_str[g], seq=self._gang_seq)

    def _gang_record_permit(self, ctx: _GangCtx, ns: str, name: str, g: int,
                            waited: bool) -> None:
        """Permit record for an admitted member: "wait" (+ the group
        timeout) for members whose rank was below quorum when they
        reached Permit — the ones a group-wide allow() released —
        "success" for the quorum-completing member and every later one."""
        if waited:
            self.result_store.add_permit_result(
                ns, name, ctx.gp_name, ann.WAIT_MESSAGE, ctx.timeout_str[g])
        else:
            self.result_store.add_permit_result(
                ns, name, ctx.gp_name, ann.SUCCESS_MESSAGE, "0s")

    def _gang_decide(self, ctx: _GangCtx, selected, lo: int, hi: int):
        """The vectorized gang-quorum pass over pending[lo:hi) (gangs
        inside are whole): ONE jnp segment-reduction computes per-group
        placed-member counts and the allow/park decision — no per-pod
        Python loop.  Returns (admit [G] bool, wait_mask [hi-lo] bool)
        and maintains the gang tracer counters."""
        from .gang import quorum_slice

        t0 = time.perf_counter()
        # child span: under commit_stream on the worker thread, under
        # commit_and_reflect on the sequential post-pass
        with TRACER.span("gang_quorum", pods=hi - lo, groups=len(ctx.keys)):
            admit, wave_counts, wait_mask = quorum_slice(
                ctx.gid[lo:hi], np.asarray(selected[lo:hi], dtype=np.int32),
                ctx.already, ctx.min_member)
        TRACER.count("gang_quorum_pass_seconds",
                     round(time.perf_counter() - t0, 6))
        for g in np.unique(ctx.gid[lo:hi]):
            g = int(g)
            if g < 0:
                continue
            if admit[g]:
                if not ctx.admitted_before[g] and g not in ctx.counted:
                    ctx.counted.add(g)
                    TRACER.count("gang_groups_admitted_total")
            elif int(wave_counts[g]) > 0:
                TRACER.count("gang_quorum_rollbacks_total")
        return admit, wait_mask

    def _gang_prescreen(self, pending: list[dict], gp, pods_all: list[dict],
                        nodes: list[dict]):
        """The Coscheduling PreFilter: reject members whose group can
        never reach quorum from current cluster state (fewer than
        minMember member pods exist, or minResources exceeds free
        cluster capacity) — recorded under prefilter-result-status like
        an in-tree PreFilter rejection, before the wave compiles.
        Returns (surviving pending, GangDirectory or None)."""
        from .gang import GangDirectory, group_key_of

        directory = GangDirectory(self.store)
        if not directory:
            return pending, None
        directory.scan_members(pods_all)
        free_cache: dict = {}

        def free_fn():
            if "v" not in free_cache:
                free_cache["v"] = self._cluster_free(nodes, pods_all)
            return free_cache["v"]

        keep: list[dict] = []
        for p in pending:
            key = group_key_of(p)
            msg = directory.prefilter_reason(key, free_fn) if key else None
            if msg is None:
                keep.append(p)
                continue
            meta = p.get("metadata") or {}
            ns, name = meta.get("namespace") or "default", meta.get("name", "")
            self.result_store.add_pre_filter_result(ns, name, gp.name, msg)
            self._mark_unschedulable(ns, name)
            self.reflector.reflect(ns, name, uid=meta.get("uid"))
        return keep, directory

    @staticmethod
    def _cluster_free(nodes: list[dict], pods_all: list[dict]) -> dict:
        """Cluster-wide free capacity (allocatable minus bound
        requests) for the minResources PreFilter check — a documented
        simplification of the upstream coscheduling quota check."""
        from ..utils.quantity import parse_cpu_milli, parse_memory_bytes

        cpu = mem = 0
        for n in nodes:
            alloc = (n.get("status") or {}).get("allocatable") or {}
            cpu += parse_cpu_milli(alloc.get("cpu") or 0)
            mem += parse_memory_bytes(alloc.get("memory") or 0)
        for p in pods_all:
            if not ((p.get("spec") or {}).get("nodeName")):
                continue
            for c in (p.get("spec") or {}).get("containers") or []:
                req = ((c.get("resources") or {}).get("requests")) or {}
                cpu -= parse_cpu_milli(req.get("cpu") or 0)
                mem -= parse_memory_bytes(req.get("memory") or 0)
        return {"cpu": cpu, "memory": mem}

    def _gang_maintain(self) -> int:
        """Cross-call gang housekeeping, run at the top of every
        schedule_pending: timeout expiry rejects whole gangs (the
        deterministic trigger member — earliest deadline, then
        (ns, name) — records "timeout", siblings record the gang
        rejection), then parked groups whose quorum is already
        satisfied by waiting+bound members alone (e.g. a PodGroup
        minMember update) bind at their assumed nodes, and parked
        members whose PodGroup vanished are released back to the
        queue as ordinary pods.  Returns #bound."""
        if not self.gang_parked:
            return 0
        gp = self._gang_plugin()
        pname = gp.name if gp is not None else "Coscheduling"
        now = time.monotonic()
        triggers: dict[tuple[str, str], _GangParked] = {}
        for rec in self.gang_parked.values():
            if rec.deadline <= now:
                cur = triggers.get(rec.group)
                if cur is None or ((rec.deadline, rec.ns, rec.name)
                                   < (cur.deadline, cur.ns, cur.name)):
                    triggers[rec.group] = rec
        for gkey in sorted(triggers):
            t = triggers[gkey]
            for rec in self._gang_take_parked(gkey):
                msg = ("timeout" if rec is t else
                       f'rejected: gang "{gkey[0]}/{gkey[1]}" timed out '
                       "before reaching quorum")
                self.result_store.add_permit_result(
                    rec.ns, rec.name, pname, msg, rec.timeout_str)
                self._mark_unschedulable(rec.ns, rec.name,
                                         fresh_node_count=True)
                self.reflector.reflect(rec.ns, rec.name, uid=rec.uid)
            TRACER.count("gang_timeout_rejects_total")
        bound = 0
        if self.gang_parked:
            from .gang import GangDirectory

            directory = GangDirectory(self.store)
            directory.scan_members(self._list_shared("pods"))
            parked_counts = self._gang_parked_counts()
            for gkey in sorted({r.group for r in self.gang_parked.values()}):
                spec = directory.specs.get(gkey)
                if spec is None:
                    # PodGroup deleted while members waited: release the
                    # park — the members reschedule as ordinary pods
                    for rec in self._gang_take_parked(gkey):
                        self.result_store.delete_data(
                            {"metadata": {"namespace": rec.ns,
                                          "name": rec.name}})
                    continue
                if (parked_counts.get(gkey, 0)
                        + directory.bound.get(gkey, 0)) >= spec.min_member:
                    for rec in self._gang_take_parked(gkey):
                        self._bind(rec.ns, rec.name, rec.node)
                        self.reflector.reflect(rec.ns, rec.name, uid=rec.uid)
                        bound += 1
        return bound

    @staticmethod
    def _observe_plugin(plugin: str, point: str, t0: float,
                        status: str) -> None:
        """Real per-plugin wall clock for host-path lifecycle calls —
        the time half of the attribution story (docs/metrics.md:
        device-fused plugins get work attribution instead)."""
        TRACER.observe("plugin_execution_duration_seconds",
                       time.perf_counter() - t0, plugin=plugin,
                       extension_point=point, status=status)

    def _run_custom_lifecycle(self, pod, ns: str, name: str, node_name: str,
                              allow_async: bool = False,
                              private: bool = False):
        """Reserve -> Permit -> PreBind -> (caller binds) -> PostBind for
        custom plugins, upstream phase ordering (all Reserves, then all
        Permits, then all PreBinds; Unreserve runs for ALL reserve plugins
        in reverse order on any failure — scheduleOne calls
        RunReservePluginsUnreserve unconditionally over the full list).
        Returns False when the pod must not bind.

        A Permit "wait" parks the pod in self.waiting_pods with the
        plugin's timeout (upstream waitingPods map); the plugin's optional
        on_waiting(handle) is invoked.  With allow_async (the batched wave
        path) the method returns "deferred" and a waiter thread finishes
        the binding cycle — PreBind, bind, PostBind, reflect — once every
        waiting plugin allowed, one rejected, or the timeout expired; the
        wave continues scheduling other pods meanwhile, like upstream's
        per-pod binding-cycle goroutines blocking in WaitOnPermit
        (reference: wrappedplugin.go:588-620 + upstream
        runtime/waiting_pods_map.go).  Without allow_async the call blocks
        until resolution (host-interleaved path)."""
        plugins = self._custom_lifecycle_plugins()
        if not plugins:
            return True
        if not private:
            # third-party plugin code must never see the store's shared
            # manifests — a mutating plugin would corrupt live cluster
            # state with no resourceVersion bump and no watch event
            pod = copy.deepcopy(pod)
        from .waiting import WaitingPod
        from ..scheduler.debuggable import has_hook
        from ..utils.duration import parse_duration_seconds

        emap = self._extenders_map()
        node = self._get_node(node_name)
        rs = self.result_store

        def unreserve_all() -> None:
            for q in reversed(plugins):
                if q.has_unreserve:
                    q.unreserve(pod, node)

        for p in plugins:
            if not p.has_reserve:
                continue
            ext = emap.get(p.name)
            if ext is not None and has_hook(ext, "before_reserve"):
                if ext.before_reserve(pod, node) is not None:
                    unreserve_all()  # plugin skipped, nothing recorded
                    return False
            t0 = time.perf_counter()
            msg = p.reserve(pod, node)
            self._observe_plugin(p.name, "reserve", t0,
                                 "Success" if not msg else "Unschedulable")
            rs.add_reserve_result(ns, name, p.name,
                                  msg if msg else ann.SUCCESS_MESSAGE)
            if ext is not None and has_hook(ext, "after_reserve"):
                msg = ext.after_reserve(pod, node, msg)  # framework outcome
            if msg:
                unreserve_all()
                return False
        waits: list[tuple] = []  # (plugin, timeout_str)
        for p in plugins:
            if not p.has_permit:
                continue
            ext = emap.get(p.name)
            if ext is not None and has_hook(ext, "before_permit"):
                if ext.before_permit(pod, node) is not None:
                    unreserve_all()
                    return False
            t0 = time.perf_counter()
            out = p.permit(pod, node)
            self._observe_plugin(
                p.name, "permit", t0,
                "Success" if out is None
                else ("Wait" if isinstance(out, tuple) else "Unschedulable"))
            if out is None:
                rs.add_permit_result(ns, name, p.name, ann.SUCCESS_MESSAGE, "0s")
            elif isinstance(out, tuple):
                rs.add_permit_result(ns, name, p.name, ann.WAIT_MESSAGE,
                                     str(out[1]))
            else:
                rs.add_permit_result(ns, name, p.name, str(out), "0s")
            if ext is not None and has_hook(ext, "after_permit"):
                out = ext.after_permit(pod, node, out)  # framework outcome
            if out is None:
                pass
            elif isinstance(out, tuple):
                waits.append((p, str(out[1])))
            else:
                unreserve_all()
                return False
        if waits:
            timeouts = {}
            for p, t in waits:
                try:
                    timeouts[p.name] = parse_duration_seconds(t)
                except ValueError:
                    timeouts[p.name] = 0.0
            wp = WaitingPod(pod, timeouts)
            self.waiting_pods[(ns, name)] = wp
            for p, _ in waits:
                on_waiting = getattr(p, "on_waiting", None)
                if callable(on_waiting):
                    on_waiting(wp)
            if allow_async:
                import threading

                t = threading.Thread(
                    target=self._waiter_finish,
                    args=(wp, waits, pod, ns, name, node_name, node, plugins,
                          emap, unreserve_all),
                    daemon=True,
                )
                with self._waiter_lock:
                    self._wait_threads.append(t)
                t.start()
                return "deferred"
            try:
                rejection = wp.wait()
            finally:
                self.waiting_pods.pop((ns, name), None)
            if rejection is not None:
                plugin_name, msg = rejection
                timeout_str = next(
                    (t for p, t in waits if p.name == plugin_name), "0s")
                rs.add_permit_result(ns, name, plugin_name, msg, timeout_str)
                unreserve_all()
                return False
        return self._lifecycle_prebind(pod, ns, name, node, plugins, emap,
                                       unreserve_all)

    def _lifecycle_prebind(self, pod, ns, name, node, plugins, emap,
                           unreserve_all) -> bool:
        from ..scheduler.debuggable import has_hook

        rs = self.result_store
        for p in plugins:
            if not p.has_pre_bind:
                continue
            ext = emap.get(p.name)
            if ext is not None and has_hook(ext, "before_pre_bind"):
                if ext.before_pre_bind(pod, node) is not None:
                    unreserve_all()
                    return False
            t0 = time.perf_counter()
            msg = p.pre_bind(pod, node)
            self._observe_plugin(p.name, "prebind", t0,
                                 "Success" if not msg else "Unschedulable")
            rs.add_pre_bind_result(ns, name, p.name,
                                   msg if msg else ann.SUCCESS_MESSAGE)
            if ext is not None and has_hook(ext, "after_pre_bind"):
                msg = ext.after_pre_bind(pod, node, msg)  # framework outcome
            if msg:
                unreserve_all()
                return False
        return True

    def _waiter_finish(self, wp, waits, pod, ns, name, node_name, node,
                       plugins, emap, unreserve_all) -> None:
        """Binding-cycle tail for a parked pod (runs on a waiter thread).

        The pod stays in self.waiting_pods until the bind (or rejection)
        has fully landed — popping earlier would let a concurrent retry
        wave re-schedule it.  Any exception resolves to "rejected" (with
        unreserve) rather than silently killing the thread."""
        with TRACER.session_scope(self.session):
            self._waiter_finish_scoped(wp, waits, pod, ns, name, node_name,
                                       node, plugins, emap, unreserve_all)

    def _waiter_finish_scoped(self, wp, waits, pod, ns, name, node_name,
                              node, plugins, emap, unreserve_all) -> None:
        outcome = "rejected"
        try:
            rejection = wp.wait()
            if rejection is not None:
                plugin_name, msg = rejection
                timeout_str = next(
                    (t for p, t in waits if p.name == plugin_name), "0s")
                self.result_store.add_permit_result(ns, name, plugin_name,
                                                    msg, timeout_str)
                unreserve_all()
            elif self._lifecycle_prebind(pod, ns, name, node, plugins, emap,
                                         unreserve_all):
                self._bind(ns, name, node_name)
                # pod here is the lifecycle's private copy
                self._run_custom_postbind(pod, node_name, private=True)
                outcome = "bound"
        except Exception:
            try:
                unreserve_all()
            # best-effort cleanup on an already-failed waiter
            # kss-analyze: allow(swallowed-exception)
            except Exception:
                pass
        finally:
            try:
                if outcome == "rejected":
                    # waiter threads resolve after the wave: the cached
                    # per-wave node count may be stale, re-count fresh
                    self._mark_unschedulable(ns, name, fresh_node_count=True)
                self.reflector.reflect(
                    ns, name, uid=(pod.get("metadata") or {}).get("uid"))
            # the waiter thread must reach its result handoff; a reflect
            # failure leaves the store record for the next reflect
            # kss-analyze: allow(swallowed-exception)
            except Exception:
                pass
            self.waiting_pods.pop((ns, name), None)
            with self._waiter_lock:
                self._waiter_results.append((outcome, ns, name))

    def _get_node(self, node_name: str) -> dict | None:
        """Private node manifest for third-party plugin calls, None when
        it vanished mid-cycle."""
        try:
            return self.store.get("nodes", node_name)
        except NotFound:
            return None

    def _unreserve_custom(self, pod, node_name: str,
                          private: bool = False) -> None:
        """Unreserve ALL custom reserve plugins in reverse order — upstream
        runs RunReservePluginsUnreserve on ANY failure after Reserve
        succeeded, including a bind failure (scheduleOne's binding-cycle
        error path)."""
        plugins = [p for p in self._custom_lifecycle_plugins() if p.has_unreserve]
        if not plugins:
            return
        if not private:
            pod = copy.deepcopy(pod)
        node = self._get_node(node_name)
        for p in reversed(plugins):
            p.unreserve(pod, node)

    def _run_custom_postbind(self, pod, node_name: str,
                             private: bool = False) -> None:
        """PostBind (observation only, after the successful bind)."""
        plugins = [p for p in self._custom_lifecycle_plugins() if p.has_post_bind]
        if not plugins:
            return
        if not private:
            pod = copy.deepcopy(pod)  # plugins must not reach shared manifests
        emap = self._extenders_map()
        node = self._get_node(node_name)
        for p in plugins:
            ext = emap.get(p.name)
            if ext is not None:
                getattr(ext, "before_post_bind", lambda *a: None)(pod, node)
            t0 = time.perf_counter()
            p.post_bind(pod, node)
            self._observe_plugin(p.name, "postbind", t0, "Success")
            if ext is not None:
                getattr(ext, "after_post_bind", lambda *a: None)(pod, node)

    def _run_postfilter(self, cw, filter_codes, pod_idx, pod, ns: str, name: str) -> bool:
        """Run DefaultPreemption for an unschedulable pod; record the
        postfilter-result; execute victims + nomination. True if a node
        was nominated (the caller then runs a retry wave).

        filter_codes: [F, N] this pod's codes over cw.config.filters()."""
        from .preemption import PLUGIN_NAME, Preemptor, first_fail_plugins

        fskip = cw.host["filter_skip"]
        filters = cw.config.filters()
        active_idx = [f for f, n in enumerate(filters) if not fskip[n][pod_idx]]
        active_names = [filters[f] for f in active_idx]
        firsts = first_fail_plugins(filter_codes[active_idx], active_names)
        failed = [
            (node, firsts[j]) for j, node in enumerate(cw.node_table.names)
            if firsts[j] is not None
        ]
        outcome = Preemptor(
            self.store, self.plugin_config,
            extender_service=self.extender_service,
        ).preempt(pod, failed)
        self.result_store.add_post_filter_result(
            ns, name, outcome.nominated_node, PLUGIN_NAME, outcome.evaluated_nodes
        )
        if not outcome.nominated_node:
            return False
        for v in outcome.victims:
            vm = v.get("metadata") or {}
            try:
                self.store.delete("pods", vm.get("name", ""), vm.get("namespace") or "default")
            # victim already gone: the preemption's goal state
            # kss-analyze: allow(swallowed-exception)
            except NotFound:
                pass

        def nominate(cur: dict) -> None:
            cur.setdefault("status", {})["nominatedNodeName"] = outcome.nominated_node

        self._update_pod(ns, name, nominate)
        return True

    def _schedule_host_path(self, cw, pending) -> tuple[int, str | None]:
        """Host-interleaved path: device eval -> plugin-extender hooks +
        extender Filter/Prioritize over HTTP -> host selection -> device
        bind.  Taken when webhook extenders are configured (the
        reference's round-trip, SURVEY.md §3.3), when a plugin extender
        intercepts an extension point (wrappedplugin.go:159-171 Before/
        After hooks), or when a custom plugin has NormalizeScore
        (arbitrary Python can't run inside the device scan)."""
        import jax

        from .pipeline import build_phased

        eval_fn, bind_fn = build_phased(cw)
        carry = jax.tree.map(lambda a: a, cw.init_carry)
        names = cw.node_table.names
        name_to_idx = {nm: j for j, nm in enumerate(names)}
        postfilter_on = bool(cw.config.postfilters())
        with TRACER.span("host_path_wave", pods=len(pending)):
            return self._host_pod_loop(
                cw, pending, eval_fn, bind_fn, carry, names, name_to_idx,
                postfilter_on)

    def _webhook_filter(self, pod, names, name_to_idx, feasible) -> bool:
        """Extender filter verbs narrow `feasible` in place; returns True
        on an unignorable extender error."""
        import numpy as np

        extenders = self.extender_service.extenders if self.extender_service else []
        for idx, ext in enumerate(extenders):
            if not ext.filter_verb or not feasible.any():
                continue
            if not ext.is_interested(pod):
                continue
            node_names = [names[j] for j in np.flatnonzero(feasible)]
            args = {"Pod": pod, "NodeNames": node_names}
            try:
                result = self.extender_service.handle("filter", idx, args)
            except Exception:
                if ext.ignorable:
                    continue
                return True
            # an Error string in the response body is a failed extender
            # call even over HTTP 200 (upstream HTTPExtender.Filter)
            if result.get("Error") or result.get("error"):
                if ext.ignorable:
                    continue
                return True
            # nodeCacheCapable extenders answer with NodeNames; the
            # default contract answers with a full Nodes list.  Per-node
            # FailedNodes reasons travel in the recorded
            # extender-filter-result annotation (handle() stored the
            # whole response).
            # canonical extender/v1 JSON tags are all-lowercase
            # ("nodenames"/"nodes"); Go-struct casing accepted for
            # hand-rolled extenders
            from ..scheduler.extender import pick_field

            kept = pick_field(result, "nodenames", "NodeNames", "nodeNames")
            if kept is None:
                nodes_obj = pick_field(result, "nodes", "Nodes")
                if nodes_obj is not None:
                    kept = [
                        ((item.get("metadata") or {}).get("name", ""))
                        for item in (nodes_obj.get("Items") or nodes_obj.get("items") or [])
                    ]
            if kept is None:
                continue  # extender restricted nothing
            keep_mask = np.zeros(len(names), bool)
            for nm in kept:
                j = name_to_idx.get(nm)
                if j is not None:
                    keep_mask[j] = True
            feasible &= keep_mask
        return False

    def _webhook_prioritize(self, pod, names, name_to_idx, feasible, total) -> None:
        import numpy as np

        extenders = self.extender_service.extenders if self.extender_service else []
        for idx, ext in enumerate(extenders):
            if not ext.prioritize_verb or feasible.sum() <= 1:
                continue
            if not ext.is_interested(pod):
                continue
            node_names = [names[j] for j in np.flatnonzero(feasible)]
            try:
                plist = self.extender_service.handle(
                    "prioritize", idx, {"Pod": pod, "NodeNames": node_names}
                )
            # upstream ignores prioritize-extender errors (the scores
            # just don't contribute)
            # kss-analyze: allow(swallowed-exception)
            except Exception:
                continue
            for entry in plist or []:
                j = name_to_idx.get(entry.get("Host") or entry.get("host", ""))
                if j is not None:
                    # reference extender.go:145: score x weight x
                    # (MaxNodeScore/MaxExtenderPriority) rescales the
                    # extender's 0-10 priority onto the 0-100 node-score
                    # range before weighting
                    total[j] += (int(entry.get("Score") or entry.get("score") or 0)
                                 * ext.weight * 10)

    def _hooked_filter_phase(self, cw, pod, pod_idx, codes, names, hooks):
        """Run Before/After filter hooks per node with the reference's
        recording contract: Before-failure skips the plugin (no record for
        it or anything after it on that node) and fails the node;
        After-rewrites change the framework outcome only (an own-failure
        rewritten to success lets LATER plugins run and record).
        Returns (eff_feasible [N] bool, filter_map for the record)."""
        import numpy as np

        from ..scheduler.debuggable import has_hook
        from ..store.decode import decode_filter_message

        pod = copy.deepcopy(pod)  # hooks must not reach shared manifests

        fskip = cw.host["filter_skip"]
        active = []  # (filter idx, name, before hook or None, after hook or None)
        for f, nm in enumerate(cw.config.filters()):
            if fskip[nm][pod_idx]:
                continue
            ext = hooks.get(nm)
            active.append((
                f, nm,
                ext.before_filter if ext is not None and has_hook(ext, "before_filter") else None,
                ext.after_filter if ext is not None and has_hook(ext, "after_filter") else None,
            ))
        n = len(names)
        eff_feasible = np.ones(n, bool)
        filter_map: dict[str, dict[str, str]] = {}
        for j in range(n):
            entry: dict[str, str] = {}
            for f, nm, before, after in active:
                if before is not None and before(pod, names[j]) is not None:
                    eff_feasible[j] = False
                    break  # plugin skipped: no record from here on
                own = int(codes[f, j])
                own_msg = None if own == 0 else decode_filter_message(
                    nm, own, j, cw.host)
                entry[nm] = (ann.PASSED_FILTER_MESSAGE if own_msg is None
                             else own_msg)
                fw_msg = after(pod, names[j], own_msg) if after is not None else own_msg
                if fw_msg is not None:
                    eff_feasible[j] = False
                    break
            if entry:
                filter_map[names[j]] = entry
        return eff_feasible, filter_map

    def _hooked_score_phase(self, cw, carry, sl, pod, pod_idx, raw, names,
                            feasible, hooks, name_to_idx):
        """AfterScore rewrites + host renormalization + AfterNormalize.
        Returns (record_final [S,N], total [N], cycle_error: bool).

        Records per the reference: score-result keeps the device originals;
        finalscore-result = normalize(AfterScore-modified raws) x weight
        (the store's AddNormalizedScoreResult runs before AfterNormalize);
        the framework total additionally reflects AfterNormalize."""
        import jax.numpy as jnp
        import numpy as np

        from .pipeline import renormalize
        from ..scheduler.debuggable import has_hook

        if hooks:
            pod = copy.deepcopy(pod)  # hooks must not reach shared manifests
        sskip = cw.host["score_skip"]
        score_names = cw.config.scorers()
        n = len(names)
        feas_idx = np.flatnonzero(feasible)
        eff_raw = np.array(raw, dtype=np.int64, copy=True)
        record_final = np.zeros_like(eff_raw)
        total = np.zeros(n, dtype=np.int64)
        feas_j = jnp.asarray(feasible)
        for s, nm in enumerate(score_names):
            if sskip[nm][pod_idx]:
                continue
            ext = hooks.get(nm)
            if ext is not None and has_hook(ext, "before_score"):
                for j in feas_idx:
                    if ext.before_score(pod, names[j]) is not None:
                        return record_final, total, True  # cycle errors
            if ext is not None and has_hook(ext, "after_score"):
                for j in feas_idx:
                    eff_raw[s, j] = int(ext.after_score(
                        pod, names[j], int(eff_raw[s, j])))
            normed = np.asarray(renormalize(
                nm, cw, carry, sl, jnp.asarray(eff_raw[s]), feas_j),
                dtype=np.int64)
            w = cw.config.weight(nm)
            record_final[s] = normed * w
            fw_norm = np.array(normed, copy=True)
            if ext is not None and has_hook(ext, "after_normalize"):
                ret = ext.after_normalize(
                    pod, {names[j]: int(fw_norm[j]) for j in feas_idx})
                if ret is not None:
                    for node_name, v in ret.items():
                        j = name_to_idx.get(node_name)
                        if j is not None:
                            fw_norm[j] = int(v)
            total += np.where(feasible, fw_norm * w, 0)
        return record_final, total, False

    def _host_pod_loop(self, cw, pending, eval_fn, bind_fn, carry, names,
                       name_to_idx, postfilter_on) -> tuple[int, str | None]:
        import jax
        import numpy as np

        from .replay import ReplayResult

        from ..scheduler.debuggable import has_hook

        hooks = self._cycle_hooks()
        custom_norm = any(
            cw.config.is_custom(nm) and getattr(cw.config.custom[nm], "has_normalize", False)
            for nm in cw.config.enabled
        )
        rescore = bool(hooks) or custom_norm
        has_filter_hooks = any(
            has_hook(ext, "before_filter") or has_hook(ext, "after_filter")
            for ext in hooks.values()
        )

        n_bound = 0
        retry: str | None = None
        for i, pod in enumerate(pending):
            sl = jax.tree.map(lambda a: a[i] if hasattr(a, "ndim") and a.ndim else a, cw.xs)
            out = eval_fn(carry, sl)
            codes = np.asarray(out.filter_codes)
            fskip = cw.host["filter_skip"]
            active = [f for f, nm in enumerate(cw.config.filters()) if not fskip[nm][i]]

            pf_reject = int(out.prefilter_reject)
            hook_filter_map = None
            if pf_reject:
                # PreFilter aborted the cycle: Filter never runs upstream,
                # so neither do Before/After filter hooks, nor extenders
                feasible = np.zeros(len(names), bool)
            elif has_filter_hooks:
                feasible, hook_filter_map = self._hooked_filter_phase(
                    cw, pod, i, codes, names, hooks)
            else:
                feasible = codes[active].max(axis=0) == 0 if active else np.ones(len(names), bool)

            meta = pod.get("metadata") or {}
            ns, name = meta.get("namespace") or "default", meta.get("name", "")
            ext_error = self._webhook_filter(pod, names, name_to_idx, feasible)

            cycle_error = False
            record_final = np.asarray(out.score_final)
            if rescore and not ext_error and int(feasible.sum()) > 1:
                record_final, total, cycle_error = self._hooked_score_phase(
                    cw, carry, sl, pod, i, np.asarray(out.score_raw), names,
                    feasible, hooks, name_to_idx)
            else:
                total = np.asarray(out.score_final).sum(axis=0).astype(np.int64)
            if not cycle_error:
                self._webhook_prioritize(pod, names, name_to_idx, feasible, total)

            count = int(feasible.sum())
            sel = -1
            if cycle_error:
                pass  # RunScorePlugins error: the cycle fails outright
            elif count == 1:
                sel = int(np.flatnonzero(feasible)[0])
            elif count > 1:
                masked = np.where(feasible, total, -1)
                sel = int(masked.argmax())

            rr1 = ReplayResult(
                cw=cw,
                filter_codes=codes[None],
                score_raw=np.asarray(out.score_raw)[None],
                score_final=np.asarray(record_final)[None],
                selected=np.asarray([sel], dtype=np.int32),
                feasible_count=np.asarray([count], dtype=np.int32),
                prefilter_reject=np.asarray([pf_reject], dtype=np.int32),
            )
            annotations = decode_pod_result(
                rr1, 0,
                feasible_override=(np.zeros_like(feasible) if cycle_error else feasible),
                host_index=i)
            if hook_filter_map is not None and not pf_reject:
                annotations[ann.FILTER_RESULT] = ann.marshal(hook_filter_map)
            self.result_store.put_decoded(ns, name, annotations)
            emap = self._extenders_map()
            if emap:
                hook_pod = copy.deepcopy(pod)  # hooks must not reach shared manifests
                for hook in emap.values():
                    hook.after_cycle(hook_pod, annotations, self.result_store)

            bind_ok = sel >= 0 and not ext_error
            lifecycle_rejected = False
            lifecycle_ok = False
            # one private copy serves every third-party surface this cycle
            priv = (copy.deepcopy(pod)
                    if bind_ok and self._custom_lifecycle_plugins() else pod)
            if bind_ok:
                if self._run_custom_lifecycle(priv, ns, name, names[sel],
                                              private=True):
                    lifecycle_ok = True
                else:
                    # here the carry only folds on a successful bind, so a
                    # rejection needs no wave re-run (sequential path)
                    bind_ok = False
                    lifecycle_rejected = True
                    sel = -1
            if bind_ok:
                bound_node = names[sel]
                extenders = self.extender_service.extenders if self.extender_service else []
                # upstream extendersBinding: the binder must also be
                # interested in the pod (IsBinder AND IsInterested)
                bind_ext = next(
                    (k for k, e in enumerate(extenders)
                     if e.bind_verb and e.is_interested(pod)),
                    None,
                )
                if bind_ext is not None:
                    # upstream: a bind-verb extender REPLACES the default
                    # binder (the wrapped DefaultBinder never runs, so its
                    # bind-result stays empty; the extender round-trip is
                    # recorded under extender-bind-result instead); its
                    # failure fails the cycle (pod retries)
                    self.result_store.put_decoded(
                        ns, name, {ann.BIND_RESULT: "{}"})
                    try:
                        result = self.extender_service.handle("bind", bind_ext, {
                            "PodName": name, "PodNamespace": ns,
                            "PodUID": meta.get("uid", ""), "Node": bound_node,
                        })
                        if (result or {}).get("Error") or (result or {}).get("error"):
                            bind_ok = False
                    except Exception:
                        bind_ok = False
                    if not bind_ok and lifecycle_ok:
                        # upstream RunReservePluginsUnreserve on bind failure
                        self._unreserve_custom(priv, bound_node, private=True)
            if bind_ok:
                carry = bind_fn(carry, sl, sel)
                self._bind(ns, name, names[sel])
                self._run_custom_postbind(priv, names[sel], private=True)
                n_bound += 1
            else:
                # FitError (no feasible node) runs PostFilter, like the
                # plain path; an extender/bind failure, a scoring-cycle
                # error, or a lifecycle rejection does not (upstream only
                # preempts on FitError).  Candidate nodes are those that
                # failed the PLUGIN filters — extender-rejected nodes are
                # not preemption candidates (docs/SEMANTICS.md).
                if (postfilter_on and sel < 0 and not ext_error
                        and not pf_reject and not lifecycle_rejected
                        and not cycle_error):
                    if self._run_postfilter(cw, codes, i, pod, ns, name):
                        retry = "preempted"
                self._mark_unschedulable(ns, name)
            self.reflector.reflect(ns, name, uid=meta.get("uid"))
        return n_bound, retry

    # ------------------------------------------------------------ writes

    def _update_pod(self, ns: str, name: str, mutate) -> None:
        """Re-fetch + mutate + update under the shared exponential-backoff
        retry (100ms x3^n, 6 steps — utils/retry.py, the reference's
        util.RetryWithExponentialBackOff schedule that the reflector's
        write path already uses).  Exhaustion raises RetryTimeout: a bind
        or status write that cannot land after 6 conflict rounds is a real
        failure and must surface, not silently drop (round-3 verdict #9).

        Copy-on-write: the callback receives a pod whose top level and
        metadata/spec/status dicts are fresh; anything deeper is SHARED
        with the stored object and must be replaced, not mutated in place
        (all current callbacks rebuild the lists they change)."""
        from ..utils.retry import retry_with_exponential_backoff

        def attempt() -> tuple[bool, Exception | None]:
            try:
                cur = self.store.get("pods", name, ns, copy_object=False)
            except NotFound:
                return True, None
            pod = dict(cur)
            pod["metadata"] = dict(cur.get("metadata") or {})
            pod["spec"] = dict(cur.get("spec") or {})
            pod["status"] = dict(cur.get("status") or {})
            mutate(pod)
            try:
                self.store.update("pods", pod, owned=True)
                return True, None
            except Conflict:
                return False, None  # re-fetch and retry under backoff

        # the reflector's stop event doubles as the engine's teardown
        # interrupt: session eviction must not ride out a bind-conflict
        # backoff (~36s) any more than a write-back one (utils/retry.py)
        retry_with_exponential_backoff(
            attempt, sleep=self._retry_sleep,
            stop=getattr(self.reflector, "stop_event", None))

    @staticmethod
    def _bind_mutation(node_name: str):
        def mutate(pod: dict) -> None:
            pod.setdefault("spec", {})["nodeName"] = node_name
            status = pod.setdefault("status", {})
            status["phase"] = "Running"  # KWOK-style: no kubelet, fake-run
            conds = [c for c in status.get("conditions") or [] if c.get("type") != "PodScheduled"]
            conds.append({"type": "PodScheduled", "status": "True"})
            status["conditions"] = conds

        return mutate

    def _bind(self, ns: str, name: str, node_name: str) -> None:
        self._update_pod(ns, name, self._bind_mutation(node_name))

    def _node_count(self, fresh: bool = False) -> int:
        """#nodes for the unschedulable condition message, cached per
        wave — _mark_unschedulable used to pay a full deepcopy
        store.list("nodes") per unschedulable pod just to render it.
        fresh=True re-counts (copy-free) for writes that land OUTSIDE
        the wave that cached it (Permit-waiter threads)."""
        n = None if fresh else self._wave_node_count
        if n is None:
            n = len(self._list_shared("nodes"))
        return n

    def _unschedulable_mutation(self, fresh_node_count: bool = False):
        n_nodes = self._node_count(fresh=fresh_node_count)

        def mutate(pod: dict) -> None:
            status = pod.setdefault("status", {})
            status["phase"] = "Pending"
            conds = [c for c in status.get("conditions") or [] if c.get("type") != "PodScheduled"]
            conds.append({
                "type": "PodScheduled", "status": "False",
                "reason": "Unschedulable",
                "message": "0/%d nodes are available" % n_nodes,
            })
            status["conditions"] = conds

        return mutate

    def _commit_pod_batch(self, items) -> int:
        """Commit a run of scheduled/unschedulable outcomes: one
        ObjectStore.apply_batch call (single lock hold, contiguous rv
        range, pod order preserved — so watch subscribers see the same
        bind order as the sequential path); per-pod _update_pod fallback
        for stores without the batch surface (the remote HTTP client).

        items: [(ns, name, node_name or None)] in pod order.  Returns
        #bound."""
        if not items:
            return 0
        bound = sum(1 for _, _, node in items if node)
        if getattr(self.store, "apply_batch", None) is None:
            for ns, name, node in items:
                if node:
                    self._bind(ns, name, node)
                else:
                    self._mark_unschedulable(ns, name)
            return bound
        unsched = None if bound == len(items) else self._unschedulable_mutation()
        self.store.apply_batch("pods", [
            (name, ns, self._bind_mutation(node) if node else unsched)
            for ns, name, node in items
        ])
        return bound

    def _mark_gated(self, ns: str, name: str) -> None:
        """upstream SchedulingGates PreEnqueue rejection condition."""
        try:
            cur = self.store.get("pods", name, ns)
        except NotFound:
            return
        conds = (cur.get("status") or {}).get("conditions") or []
        if any(c.get("reason") == "SchedulingGated" for c in conds):
            return  # already marked; don't churn resourceVersion each wave

        def mutate(pod: dict) -> None:
            status = pod.setdefault("status", {})
            status["phase"] = "Pending"
            cs = [c for c in status.get("conditions") or [] if c.get("type") != "PodScheduled"]
            cs.append({
                "type": "PodScheduled", "status": "False",
                "reason": "SchedulingGated",
                "message": "Scheduling is blocked due to non-empty scheduling gates",
            })
            status["conditions"] = cs

        self._update_pod(ns, name, mutate)

    def _mark_unschedulable(self, ns: str, name: str,
                            fresh_node_count: bool = False) -> None:
        self._update_pod(
            ns, name, self._unschedulable_mutation(fresh_node_count))
