"""Scheduling engine: drives the tensor pipeline against the cluster store.

This is the in-process equivalent of the reference's debuggable-scheduler
process (SURVEY.md §3.2): it takes pending pods from the cluster, runs the
batched Filter/Score program, binds the chosen nodes, deposits the decoded
result annotations in the result store, and triggers the reflector —
replacing the informer round-trip of the reference (storereflector
registers a Pod-update handler; binding IS the update that triggers it).

Queue order follows the PrioritySort queue-sort plugin: descending
.spec.priority, FIFO within equal priority (upstream
pkg/scheduler/framework/plugins/queuesort).  Unschedulable pods get the
PodScheduled=False/Unschedulable condition, like the scheduler's status
update, which also carries their result annotations out.
"""

from __future__ import annotations

import time

from .replay import replay
from ..cluster.store import Conflict, NotFound, ObjectStore
from ..plugins.registry import PluginSetConfig
from ..state.compile import compile_workload
from ..store.decode import decode_pod_result
from ..store.reflector import StoreReflector
from ..store.resultstore import ResultStore

RESULT_STORE_KEY = "PluginResultStoreKey"  # reference: plugins.go:23


class SchedulerEngine:
    def __init__(self, store: ObjectStore, reflector: StoreReflector | None = None,
                 result_store: ResultStore | None = None,
                 plugin_config: PluginSetConfig | None = None,
                 chunk: int = 512):
        self.store = store
        self.result_store = result_store or ResultStore()
        self.reflector = reflector or StoreReflector(store)
        if RESULT_STORE_KEY not in self.reflector.result_stores:
            self.reflector.add_result_store(self.result_store, RESULT_STORE_KEY)
        self.plugin_config = plugin_config or PluginSetConfig()
        self.chunk = chunk

    def set_plugin_config(self, cfg: PluginSetConfig) -> None:
        # validates by constructing; the service uses this for rollback
        self.plugin_config = PluginSetConfig(enabled=list(cfg.enabled), weights=dict(cfg.weights))

    # ------------------------------------------------------------ run

    def pending_pods(self) -> list[dict]:
        pods, _ = self.store.list("pods")
        pending = [p for p in pods if not ((p.get("spec") or {}).get("nodeName"))]
        # PrioritySort: priority desc, FIFO (creation resourceVersion) within
        pending.sort(
            key=lambda p: (
                -int((p.get("spec") or {}).get("priority") or 0),
                int((p.get("metadata") or {}).get("resourceVersion") or 0),
            )
        )
        return pending

    def schedule_pending(self, collect: bool = True) -> int:
        """One scheduling wave over all pending pods. Returns #bound."""
        pending = self.pending_pods()
        if not pending:
            return 0
        nodes, _ = self.store.list("nodes")
        pods_all, _ = self.store.list("pods")
        bound = [
            (p, p["spec"]["nodeName"]) for p in pods_all
            if (p.get("spec") or {}).get("nodeName")
        ]
        cw = compile_workload(nodes, pending, self.plugin_config, bound_pods=bound)
        rr = replay(cw, chunk=min(self.chunk, max(len(pending), 1)))

        n_bound = 0
        for i, pod in enumerate(pending):
            meta = pod.get("metadata") or {}
            ns, name = meta.get("namespace") or "default", meta.get("name", "")
            annotations = decode_pod_result(rr, i)
            self.result_store.put_decoded(ns, name, annotations)
            sel = int(rr.selected[i])
            if sel >= 0:
                self._bind(ns, name, cw.node_table.names[sel])
                n_bound += 1
            else:
                self._mark_unschedulable(ns, name)
            self.reflector.reflect(ns, name)
        return n_bound

    # ------------------------------------------------------------ writes

    def _bind(self, ns: str, name: str, node_name: str) -> None:
        for _ in range(5):
            try:
                pod = self.store.get("pods", name, ns)
            except NotFound:
                return
            pod.setdefault("spec", {})["nodeName"] = node_name
            status = pod.setdefault("status", {})
            status["phase"] = "Running"  # KWOK-style: no kubelet, fake-run
            conds = [c for c in status.get("conditions") or [] if c.get("type") != "PodScheduled"]
            conds.append({"type": "PodScheduled", "status": "True"})
            status["conditions"] = conds
            try:
                self.store.update("pods", pod)
                return
            except Conflict:
                time.sleep(0.001)

    def _mark_unschedulable(self, ns: str, name: str) -> None:
        for _ in range(5):
            try:
                pod = self.store.get("pods", name, ns)
            except NotFound:
                return
            status = pod.setdefault("status", {})
            status["phase"] = "Pending"
            conds = [c for c in status.get("conditions") or [] if c.get("type") != "PodScheduled"]
            conds.append({
                "type": "PodScheduled", "status": "False",
                "reason": "Unschedulable",
                "message": "0/%d nodes are available" % len(self.store.list("nodes")[0]),
            })
            status["conditions"] = conds
            try:
                self.store.update("pods", pod)
                return
            except Conflict:
                time.sleep(0.001)
