"""The scheduling cycle as one fused tensor program.

The reference's hot path (SURVEY.md §3.2) is, per pod:

    RunPreFilterPlugins -> Filter x (plugins x nodes) [16 goroutines]
    -> RunPreScorePlugins -> Score x (plugins x nodes) -> NormalizeScore
    -> weights -> selectHost -> Reserve/Bind

Here `build_step(cw)` composes, at trace time, the enabled plugins' tensor
kernels into a single step function

    step(carry, xs_slice) -> (carry', StepOut)

with NO plugin dispatch on device: XLA sees one fused program over [N]-
shaped arrays.  `lax.scan`ning it over the pod axis replays a whole queue
in one XLA call (framework/replay.py), because scheduling is inherently
sequential across pods — each bind mutates node state — while fully
parallel across nodes and plugins.

Fidelity notes
  * Filter plugins run in upstream order; the framework stops at the first
    failing plugin per node — all masks are computed here (cheaper than
    branching on TPU) and the stop-at-first-fail truncation is
    reconstructed by the annotation decoder (store/decode.py).
  * Scoring runs only when >1 node is feasible (upstream schedulePod
    returns early on a single feasible node); on device we always compute
    and the decoder drops the results, but selection respects it.
  * Host selection: highest weighted-normalized total; ties broken by
    LOWEST node index (upstream picks randomly among ties via reservoir
    sampling — deterministic tie-break is this framework's documented
    divergence, applied identically in the CPU reference).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

from ..plugins import (
    affinity, imagelocality, interpod, noderesources, nodevolumelimits, ports,
    taints, topologyspread, volumebinding, volumerestrictions, volumezone,
)
from ..plugins.registry import PLUGIN_REGISTRY
from ..state.compile import CompiledWorkload


class StepOut(NamedTuple):
    filter_codes: jnp.ndarray  # [F, N] int32, 0 == pass (already skip-masked)
    score_raw: jnp.ndarray     # [S, N] int32
    score_final: jnp.ndarray   # [S, N] int32 (normalized x weight)
    selected: jnp.ndarray      # int32, -1 == unschedulable
    feasible_count: jnp.ndarray  # int32
    prefilter_reject: jnp.ndarray  # int32, >0 == dynamic PreFilter reject
    #   (currently only VolumeRestrictions' cluster-wide ReadWriteOncePod
    #   conflict; the decoder maps 1 -> its message)


class CompactOut(NamedTuple):
    """Transfer-optimized step output (framework/replay.py collect path).

    The annotation decoder only ever needs, per node, the FIRST failing
    filter plugin and its code (the framework stops at the first failure;
    everything before it records "passed"), so all F filter codes pack
    into one integer per node — as small as uint8 when the compile-time
    code bounds allow (PACK_MODES); PodTopologySpread's ignore mask is
    static (dom_idx + the pod's scored slots) and is recomputed on host
    rather than transferred.  finalscore is a pure
    host-recomputable function of the raw scores + feasibility
    (framework/hostnorm.py), so only raw travels — split into int8/int16
    dtype groups by compile-time per-plugin bounds
    (state/compile.py score_dtypes) with an overflow flag that triggers a
    wide (int32) rerun.  Net: ~6x less device->host payload, which is the
    end-to-end bottleneck on a tunneled TPU link.
    """

    packed_filter: jnp.ndarray   # [N]; 0 = all filter plugins passed
    raw8: jnp.ndarray            # [S8, N] int8 raw scores (provably |x|<=127)
    raw16: jnp.ndarray           # [S16, N] int16 raw scores
    raw32: jnp.ndarray           # [S32, N] int32 raw scores (wide rerun)
    raw_overflow: jnp.ndarray    # bool: some raw didn't fit its group dtype
    selected: jnp.ndarray        # int32, -1 == unschedulable
    feasible_count: jnp.ndarray  # int32
    prefilter_reject: jnp.ndarray  # int32


# packed-filter layouts: mode -> (dtype, code bits, ff bits).
# Layout (LSB first): [code][first_fail_idx + 1].  A word of 0 means
# "all filter plugins passed".
PACK_MODES = {
    "p8": (jnp.uint8, 5, 3),
    "p16": (jnp.uint16, 8, 8),
    "p32": (jnp.int32, 16, 15),
    "p64": (jnp.int64, 32, 16),
}


def choose_pack_mode(max_code: int, n_filters: int) -> str:
    for mode in ("p8", "p16", "p32", "p64"):
        _, code_bits, ff_bits = PACK_MODES[mode]
        # the packed word stores first_fail_idx + 1, max value n_filters
        if max_code < (1 << code_bits) and n_filters < (1 << ff_bits):
            return mode
    return "p64"


def _filter_one(name: str, cw: CompiledWorkload, carry, sl) -> jnp.ndarray:
    if cw.config.is_custom(name):
        return sl[name].codes.astype(jnp.int32)
    if name == "NodeResourcesFit":
        return noderesources.fit_filter(cw.statics["core"], sl["core"], carry["core"])
    if name == "NodeAffinity":
        return affinity.filter_kernel(cw.statics["NodeAffinity"], sl["NodeAffinity"])
    if name == "TaintToleration":
        return taints.taint_filter(sl["TaintToleration"])
    if name == "NodeUnschedulable":
        return taints.unsched_filter(sl["NodeUnschedulable"])
    if name == "NodeName":
        return taints.nodename_filter(sl["NodeName"])
    if name == "NodePorts":
        return ports.filter_kernel(cw.statics["NodePorts"], sl["NodePorts"], carry["NodePorts"])
    if name == "PodTopologySpread":
        return topologyspread.filter_kernel(
            cw.statics["PodTopologySpread"], sl["PodTopologySpread"], carry["PodTopologySpread"]
        )
    if name == "InterPodAffinity":
        return interpod.filter_kernel(
            cw.statics["InterPodAffinity"], sl["InterPodAffinity"], carry["InterPodAffinity"]
        )
    if name == "VolumeRestrictions":
        return volumerestrictions.filter_kernel(
            cw.statics["VolumeRestrictions"], sl["VolumeRestrictions"],
            carry["VolumeRestrictions"],
        )
    if name == "NodeVolumeLimits":
        return nodevolumelimits.filter_kernel(
            cw.statics["NodeVolumeLimits"], sl["NodeVolumeLimits"],
            carry["NodeVolumeLimits"],
        )
    if name == "VolumeBinding":
        return volumebinding.filter_kernel(
            cw.statics["VolumeBinding"], sl["VolumeBinding"], carry["VolumeBinding"]
        )
    if name == "VolumeZone":
        return volumezone.filter_kernel(sl["VolumeZone"])
    raise ValueError(f"no filter kernel for {name}")


def _score_one(name: str, cw: CompiledWorkload, carry, sl, feasible):
    """-> (raw int64 [N], normalized int64 [N])."""
    if cw.config.is_custom(name):
        raw = sl[name].scores.astype(jnp.int64)
        # a custom NormalizeScore cannot run inside the scan; the engine
        # routes such configs to the host path (engine._needs_host_path)
        # and replay() refuses them (framework/replay.py guard)
        return raw, raw
    if name == "NodeResourcesFit":
        from ..plugins.fitscoring import parse_fit_strategy

        raw = noderesources.fit_score(
            cw.statics["core"], sl["core"], carry["core"],
            strategy=parse_fit_strategy(cw.config.args.get(name)),
            schema=getattr(cw, "schema", None))
        return raw, raw  # no ScoreExtensions
    if name == "NodeResourcesBalancedAllocation":
        from ..plugins.fitscoring import parse_balanced_resources

        raw = noderesources.balanced_score(
            cw.statics["core"], sl["core"], carry["core"],
            resources=parse_balanced_resources(cw.config.args.get(name)),
            schema=getattr(cw, "schema", None))
        return raw, raw  # no ScoreExtensions
    if name == "ImageLocality":
        raw = imagelocality.score_kernel(sl["ImageLocality"])
        return raw, raw  # no ScoreExtensions
    if name == "VolumeBinding":
        raw = volumebinding.score_kernel(cw.n_nodes)
        return raw, raw  # scorer nil with VolumeCapacityPriority off
    if name == "NodeAffinity":
        raw = affinity.score_kernel(cw.statics["NodeAffinity"], sl["NodeAffinity"])
        return raw, affinity.normalize(raw, feasible)
    if name == "TaintToleration":
        raw = taints.taint_score(sl["TaintToleration"])
        return raw, taints.taint_normalize(raw, feasible)
    if name == "PodTopologySpread":
        raw, ignored = topologyspread.score_kernel(
            cw.statics["PodTopologySpread"], sl["PodTopologySpread"], carry["PodTopologySpread"]
        )
        return raw, topologyspread.normalize(raw, ignored, feasible)
    if name == "InterPodAffinity":
        raw = interpod.score_kernel(
            cw.statics["InterPodAffinity"], sl["InterPodAffinity"], carry["InterPodAffinity"]
        )
        return raw, interpod.normalize(raw, feasible)
    raise ValueError(f"no score kernel for {name}")


def renormalize(name: str, cw, carry, sl, raw, feasible):
    """Host-side NormalizeScore recompute for one plugin: [N] raw scores
    (possibly hook-modified) + feasibility -> [N] normalized.  Used by the
    host-interleaved path when AfterScore hooks or hook-changed
    feasibility invalidate the device's fused normalization, and for
    custom plugins' NormalizeScore (arbitrary Python cannot run inside the
    device scan; upstream wraps out-of-tree ScoreExtensions the same as
    in-tree, wrappedplugin.go:388-415)."""
    import numpy as np

    if cw.config.is_custom(name):
        plugin = cw.config.custom[name]
        if getattr(plugin, "has_normalize", False):
            raw_np = np.asarray(raw)
            feas = np.asarray(feasible)
            idx = np.flatnonzero(feas)
            vals = plugin.normalize([int(raw_np[j]) for j in idx])
            out = np.zeros_like(raw_np)
            out[idx] = np.asarray(list(vals), dtype=out.dtype)
            return jnp.asarray(out)
        return raw
    if name == "NodeAffinity":
        return affinity.normalize(raw, feasible)
    if name == "TaintToleration":
        return taints.taint_normalize(raw, feasible)
    if name == "InterPodAffinity":
        return interpod.normalize(raw, feasible)
    if name == "PodTopologySpread":
        _, ignored = topologyspread.score_kernel(
            cw.statics["PodTopologySpread"], sl["PodTopologySpread"],
            carry["PodTopologySpread"])
        return topologyspread.normalize(raw, ignored, feasible)
    return raw  # no ScoreExtensions


def _filter_phase(cw, carry, sl, filter_names):
    """filters in config order -> ([F, N] codes, [N] feasible)."""
    n = cw.n_nodes
    codes = []
    feasible = jnp.ones(n, dtype=bool)
    for name in filter_names:
        # broadcast: compact builders emit [1]-shaped always-pass codes
        code = jnp.broadcast_to(_filter_one(name, cw, carry, sl), (n,))
        x = sl.get(name)
        if x is not None and hasattr(x, "filter_skip"):
            code = jnp.where(x.filter_skip, 0, code)
        codes.append(code)
        feasible = feasible & (code == 0)
    filter_codes = jnp.stack(codes) if codes else jnp.zeros((0, n), dtype=jnp.int32)
    return filter_codes, feasible


def _score_phase(cw, carry, sl, weights, score_names, feasible):
    """score -> normalize -> weight over whatever node set the inputs
    cover: the full [N] axis on the scan path, or a GATHERED candidate
    subset (parallel/speculative.py sparse tail — cw/carry/sl node-axis
    leaves pre-gathered, `feasible` marking the valid rows; the
    normalizations reduce over the feasible set only, so the subset
    result is bit-identical to the dense one at those positions).
    Returns (score_raw [S, n], score_final [S, n], total [n] with
    infeasible forced to -1)."""
    n = feasible.shape[0]
    raws, finals = [], []
    total = jnp.zeros(n, dtype=jnp.int64)
    for i, name in enumerate(score_names):
        raw, normed = _score_one(name, cw, carry, sl, feasible)
        final = normed * weights[i]
        x = sl.get(name)
        if x is not None and hasattr(x, "score_skip"):
            skip = x.score_skip
            raw = jnp.where(skip, 0, raw)
            final = jnp.where(skip, 0, final)
        raws.append(raw)
        finals.append(final)
        total = total + final
    score_raw = jnp.stack(raws) if raws else jnp.zeros((0, n), dtype=jnp.int64)
    score_final = jnp.stack(finals) if finals else jnp.zeros((0, n), dtype=jnp.int64)
    total = jnp.where(feasible, total, jnp.int64(-1))
    return score_raw, score_final, total


def _eval_phase(cw: CompiledWorkload, carry, sl, weights, filter_names, score_names):
    """filter -> score -> normalize -> weight. Returns
    (filter_codes [F,N], score_raw [S,N], score_final [S,N], feasible [N],
    total [N] with infeasible forced to -1)."""
    filter_codes, feasible = _filter_phase(cw, carry, sl, filter_names)
    score_raw, score_final, total = _score_phase(
        cw, carry, sl, weights, score_names, feasible)
    return filter_codes, score_raw, score_final, feasible, total


def _bind_phase(cw: CompiledWorkload, carry, sl, selected):
    """Apply a bind of this pod to node `selected` (-1: no-op)."""
    new_carry = dict(carry)
    new_carry["core"] = noderesources.core_bind_update(carry["core"], sl["core"], selected)
    if "NodePorts" in carry:
        new_carry["NodePorts"] = ports.bind_update(
            cw.statics["NodePorts"], sl["NodePorts"], carry["NodePorts"], selected
        )
    if "PodTopologySpread" in carry:
        new_carry["PodTopologySpread"] = topologyspread.bind_update(
            cw.statics["PodTopologySpread"], sl["PodTopologySpread"],
            carry["PodTopologySpread"], selected,
        )
    if "InterPodAffinity" in carry:
        new_carry["InterPodAffinity"] = interpod.bind_update(
            cw.statics["InterPodAffinity"], sl["InterPodAffinity"],
            carry["InterPodAffinity"], selected,
        )
    if "VolumeRestrictions" in carry:
        new_carry["VolumeRestrictions"] = volumerestrictions.bind_update(
            sl["VolumeRestrictions"], carry["VolumeRestrictions"], selected
        )
    if "NodeVolumeLimits" in carry:
        new_carry["NodeVolumeLimits"] = nodevolumelimits.bind_update(
            sl["NodeVolumeLimits"], carry["NodeVolumeLimits"], selected
        )
    if "VolumeBinding" in carry:
        new_carry["VolumeBinding"] = volumebinding.bind_update(
            cw.statics["VolumeBinding"], sl["VolumeBinding"],
            carry["VolumeBinding"], selected,
        )
    return new_carry


def _prefilter_reject(cw, carry, sl) -> jnp.ndarray:
    """Dynamic (replay-state-dependent) PreFilter rejects + the static
    compile-time ones (xs['force_unsched']).  >0 forces selected = -1."""
    code = jnp.int32(0)
    if "VolumeRestrictions" in carry:
        # bit 0: ReadWriteOncePod conflict (dynamic)
        code = volumerestrictions.prefilter_reject(
            sl["VolumeRestrictions"], carry["VolumeRestrictions"]
        )
    force = sl.get("force_unsched")
    if force is not None:
        # bit 1: compile-time reject; both bits can be set — the decoder
        # resolves plugin attribution in prefilter order
        code = code | jnp.where(force, jnp.int32(2), 0)
    return code


def pack_filter_codes(filter_codes: jnp.ndarray, n: int, mode: str) -> jnp.ndarray:
    """[F, N] codes -> [N] packed first-fail word (see PACK_MODES): 0 =
    all pass, else (first_fail_idx + 1) << code_bits | code."""
    dtype, code_bits, _ = PACK_MODES[mode]
    acc_dtype = jnp.int64 if mode == "p64" else jnp.int32
    if filter_codes.shape[0] == 0:
        packed = jnp.zeros(n, dtype=acc_dtype)
    else:
        fail = filter_codes != 0
        any_fail = fail.any(axis=0)
        ff = jnp.argmax(fail, axis=0)  # first True == lowest plugin index
        code_at = jnp.take_along_axis(filter_codes, ff[None, :], axis=0)[0]
        packed = jnp.where(
            any_fail,
            ((ff.astype(acc_dtype) + 1) << code_bits) | code_at.astype(acc_dtype),
            0,
        )
    return packed.astype(dtype)


def build_step(cw, out_mode: str = "full", pack_mode: str = "p16",
               score_dtypes: tuple = (), wide_raw: str | None = None):
    """Returns step(carry_dict, xs_slice_dict) -> (carry', out).

    cw: CompiledWorkload or any object with .config/.statics/.n_nodes
    (replay passes a slim view so cached jits don't pin per-pod data).
    out_mode "full" -> StepOut; "compact" -> CompactOut (first-fail-packed
    filters, narrow raw scores, no finalscore — see CompactOut).
    score_dtypes: per-scorer "i8"/"i16"/"i32"/"host" group assignment
    (compact mode; "host" = the raw is a precompiled host-resident row and
    is omitted from the device outputs entirely);
    wide_raw "i32"/"i64" pools every transferred scorer into the raw32
    field at that width after an overflow (the replay's widening ladder)."""
    cfg = cw.config
    filter_names = cfg.filters()
    score_names = cfg.scorers()
    weights = jnp.asarray([cfg.weight(n) for n in score_names], dtype=jnp.int64)

    def step(carry: dict[str, Any], sl: dict[str, Any]):
        filter_codes, score_raw, score_final, feasible, total = _eval_phase(
            cw, carry, sl, weights, filter_names, score_names
        )
        reject = _prefilter_reject(cw, carry, sl)
        feasible_count = jnp.sum(feasible, dtype=jnp.int32)
        feasible_count = jnp.where(reject > 0, 0, feasible_count)
        selected = jnp.argmax(total).astype(jnp.int32)  # first max == lowest index
        selected = jnp.where(feasible_count > 0, selected, jnp.int32(-1))
        is_pad = sl.get("is_pad")
        if is_pad is not None:
            selected = jnp.where(is_pad, jnp.int32(-1), selected)

        new_carry = _bind_phase(cw, carry, sl, selected)
        if out_mode == "compact":
            groups: dict[str, list] = {"i8": [], "i16": [], "i32": []}
            for s in range(len(score_names)):
                g = score_dtypes[s]
                if g == "host":
                    continue  # precompiled host row: never travels D2H
                g = "i32" if wide_raw else g
                groups[g].append(score_raw[s])
            n = cw.n_nodes

            def stack(rows, dtype):
                if not rows:
                    return jnp.zeros((0, n), dtype=dtype)
                return jnp.stack(rows).astype(dtype)

            raw8 = stack(groups["i8"], jnp.int8)
            raw16 = stack(groups["i16"], jnp.int16)
            raw32 = stack(groups["i32"],
                          jnp.int64 if wide_raw == "i64" else jnp.int32)
            ovf = jnp.asarray(False)
            if wide_raw is None and groups["i16"]:
                # i8 members are provably in range (compile-time bounds);
                # only the i16 group needs the runtime check
                full = jnp.stack(groups["i16"])
                ovf = jnp.any(full != raw16.astype(full.dtype))
            elif wide_raw == "i32" and groups["i32"]:
                # custom scorers can exceed int32 (upstream scores are
                # int64): keep checking so the ladder can reach i64
                full = jnp.stack(groups["i32"])
                ovf = jnp.any(full != raw32.astype(full.dtype))
            out: Any = CompactOut(
                packed_filter=pack_filter_codes(filter_codes, n, pack_mode),
                raw8=raw8,
                raw16=raw16,
                raw32=raw32,
                raw_overflow=ovf,
                selected=selected,
                feasible_count=feasible_count,
                prefilter_reject=reject,
            )
        else:
            out = StepOut(
                filter_codes=filter_codes.astype(jnp.int32),
                score_raw=score_raw.astype(jnp.int32),
                score_final=score_final.astype(jnp.int32),
                selected=selected,
                feasible_count=feasible_count,
                prefilter_reject=reject,
            )
        return new_carry, out

    return step


def build_phased(cw: CompiledWorkload):
    """(eval_fn, bind_fn) for host-interleaved phases — the extender path:
    the host can veto/boost nodes between the device's score phase and the
    bind (reference extender round-trip, SURVEY.md §3.3).

      eval_fn(carry, xs_slice) -> StepOut (selected = the device's own
                                  choice, advisory; carry NOT updated)
      bind_fn(carry, xs_slice, selected int32) -> carry'
    """
    import jax

    cfg = cw.config
    filter_names = cfg.filters()
    score_names = cfg.scorers()
    weights = jnp.asarray([cfg.weight(n) for n in score_names], dtype=jnp.int64)

    def eval_fn(carry, sl):
        filter_codes, score_raw, score_final, feasible, total = _eval_phase(
            cw, carry, sl, weights, filter_names, score_names
        )
        reject = _prefilter_reject(cw, carry, sl)
        feasible_count = jnp.sum(feasible, dtype=jnp.int32)
        feasible_count = jnp.where(reject > 0, 0, feasible_count)
        selected = jnp.argmax(total).astype(jnp.int32)
        selected = jnp.where(feasible_count > 0, selected, jnp.int32(-1))
        return StepOut(
            filter_codes=filter_codes.astype(jnp.int32),
            score_raw=score_raw.astype(jnp.int32),
            score_final=score_final.astype(jnp.int32),
            selected=selected,
            feasible_count=feasible_count,
            prefilter_reject=reject,
        )

    def bind_fn(carry, sl, selected):
        return _bind_phase(cw, carry, sl, jnp.asarray(selected, dtype=jnp.int32))

    return jax.jit(eval_fn), jax.jit(bind_fn)
