from .pipeline import build_step, StepOut  # noqa: F401
from .replay import replay, ReplayResult  # noqa: F401
