"""Waiting pods — real Permit "wait" semantics.

Upstream, a Permit plugin returning Wait parks the pod in the framework's
waitingPods map; other plugins (or any holder of the framework handle) can
Allow/Reject it per plugin, and an expired timeout rejects the pod
(reference: simulator/scheduler/plugin/wrappedplugin.go:588-620 records
the "wait" status and the timeout into permit-result / permit-result-timeout;
the park/allow/reject machinery is upstream
k8s.io/kubernetes pkg/scheduler/framework/runtime/waiting_pods_map.go).

Here the engine parks the pod in ``SchedulerEngine.waiting_pods`` keyed by
(namespace, name); each waiting plugin may observe the handle via an
optional ``on_waiting(waiting_pod)`` method (the in-process analogue of a
goroutine holding the framework handle), and the engine then blocks until
every pending plugin allowed, any rejected, or the shortest per-plugin
timeout expired.
"""

from __future__ import annotations

import threading
import time


class WaitingPod:
    """Handle for a pod parked by Permit "wait" statuses.

    allow(plugin)/reject(plugin, msg) may be called from any thread (the
    analogue of upstream's WaitingPod interface)."""

    def __init__(self, pod: dict, plugin_timeouts: dict[str, float]):
        self.pod = pod
        now = time.monotonic()
        self._deadlines = {p: now + t for p, t in plugin_timeouts.items()}
        self._rejected: tuple[str, str] | None = None
        self._cv = threading.Condition()

    def pending_plugins(self) -> list[str]:
        with self._cv:
            return list(self._deadlines)

    def allow(self, plugin_name: str) -> None:
        with self._cv:
            self._deadlines.pop(plugin_name, None)
            self._cv.notify_all()

    def reject(self, plugin_name: str, msg: str = "rejected") -> None:
        """First rejection wins (a concurrent second reject cannot
        change the recorded plugin/message); pending deadlines are
        cleared so the handle reads as settled afterwards."""
        with self._cv:
            if self._rejected is None:
                self._rejected = (plugin_name, msg)
            self._deadlines.clear()
            self._cv.notify_all()

    def wait(self) -> tuple[str, str] | None:
        """Block until resolved. None == allowed by everyone; otherwise
        (plugin, message) for an explicit reject or a timeout expiry.

        Timeout selection is deterministic — earliest deadline, then
        plugin name — so the plugin recorded into permit-result-timeout
        is reproducible regardless of dict iteration order; the expiry
        settles the handle (a second wait() returns the same
        rejection)."""
        with self._cv:
            while True:
                if self._rejected is not None:
                    return self._rejected
                if not self._deadlines:
                    return None
                now = time.monotonic()
                expired = sorted(
                    (d, p) for p, d in self._deadlines.items() if d <= now)
                if expired:
                    # upstream: timeout rejects the waiting pod
                    self._rejected = (expired[0][1], "timeout")
                    self._deadlines.clear()
                    return self._rejected
                self._cv.wait(timeout=min(self._deadlines.values()) - now)
