"""Real kube-apiserver client: the import/sync/record source adapter.

The reference's importer, syncer, and recorder take client-go dynamic
clients against any real cluster (reference:
simulator/oneshotimporter/importer.go:29-37, syncer/syncer.go:53-74,
cmd/sched-recorder/recorder.go:69-93; headline feature in
simulator/docs/import-cluster-resources.md:1-55).  This module is that
capability for this framework: `KubeAPICluster` speaks the kube-apiserver
REST protocol — list with labelSelector and resourceVersion, streaming
watch with resume and 410-Gone recovery, kubeconfig auth (token, basic,
client certificates, CA pinning, insecure-skip-verify) — and implements
the same read interface as `cluster.store.ObjectStore`
(get/list/watch/unwatch, plus create/update/delete for completeness), so
`OneShotImporter`, `SyncerService`, and `RecorderService` can point at a
production cluster unchanged.

Event tuples match ObjectStore.watch: (rv, event_type, obj) with
event_type in {ADDED, MODIFIED, DELETED}.  Real resourceVersions are
opaque strings; they are exposed as ints when they parse (etcd rvs do),
else a per-client monotonic counter stands in — consumers only use rv
for ordering/resume diagnostics, resume itself keeps the server's exact
string.

No kubernetes client library is required (none is vendored here — the
protocol is plain HTTPS + JSON, which is the point of the adapter).
"""

from __future__ import annotations

import base64
import json
import queue
import ssl
import tempfile
import threading
import urllib.error
import urllib.parse
import urllib.request

from .store import (
    ADDED,
    ApiError,
    AlreadyExists,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    RESOURCES,
)

# GVR -> (API path prefix, namespaced).  The simulator's seven GVRs
# (reference: recorder/recorder.go:45-53) live in three API groups.
API_PATHS: dict[str, tuple[str, bool]] = {
    "namespaces": ("/api/v1", False),
    "nodes": ("/api/v1", False),
    "pods": ("/api/v1", True),
    "persistentvolumes": ("/api/v1", False),
    "persistentvolumeclaims": ("/api/v1", True),
    "priorityclasses": ("/apis/scheduling.k8s.io/v1", False),
    "storageclasses": ("/apis/storage.k8s.io/v1", False),
}

_WATCH_TYPES = {"ADDED": ADDED, "MODIFIED": MODIFIED, "DELETED": DELETED}


def _label_selector_str(sel) -> str:
    """dict {k: v} or metav1.LabelSelector-shaped dict -> selector string."""
    if not sel:
        return ""
    if isinstance(sel, str):
        return sel
    if "matchLabels" in sel or "matchExpressions" in sel:
        parts = [f"{k}={v}" for k, v in (sel.get("matchLabels") or {}).items()]
        for e in sel.get("matchExpressions") or []:
            op = (e.get("operator") or "In").lower()
            key = e.get("key", "")
            vals = ",".join(e.get("values") or [])
            if op == "in":
                parts.append(f"{key} in ({vals})")
            elif op == "notin":
                parts.append(f"{key} notin ({vals})")
            elif op == "exists":
                parts.append(key)
            elif op == "doesnotexist":
                parts.append(f"!{key}")
        return ",".join(parts)
    return ",".join(f"{k}={v}" for k, v in sel.items())


def _data_or_file(data_b64: str | None, path: str | None,
                  keep: list) -> str | None:
    """Inline base64 kubeconfig data -> temp file (ssl wants paths).
    Files land in `keep` and are unlinked by the caller the moment the
    SSL context has loaded them — key material must not linger in
    $TMPDIR."""
    if data_b64:
        f = tempfile.NamedTemporaryFile(suffix=".pem", delete=False)
        f.write(base64.b64decode(data_b64))
        f.flush()
        keep.append(f)
        return f.name
    return path


def load_kubeconfig(path: str, context: str | None = None):
    """Parse a kubeconfig -> (server_url, ssl.SSLContext | None, headers).

    Supports the fields the reference's clientcmd path exercises for the
    simulator: cluster.server, certificate-authority(-data),
    insecure-skip-tls-verify; user.token, username/password,
    client-certificate(-data) + client-key(-data)."""
    import yaml

    with open(path) as f:
        kc = yaml.safe_load(f) or {}
    ctx_name = context or kc.get("current-context") or ""
    ctx = next((c["context"] for c in kc.get("contexts") or []
                if c.get("name") == ctx_name), None)
    if ctx is None:
        raise ValueError(f"kubeconfig: context {ctx_name!r} not found")
    cluster = next((c["cluster"] for c in kc.get("clusters") or []
                    if c.get("name") == ctx.get("cluster")), None)
    if cluster is None or not cluster.get("server"):
        raise ValueError("kubeconfig: cluster/server missing")
    user = next((u["user"] for u in kc.get("users") or []
                 if u.get("name") == ctx.get("user")), {}) or {}

    server = cluster["server"].rstrip("/")
    headers: dict[str, str] = {}
    if user.get("token"):
        headers["Authorization"] = f"Bearer {user['token']}"
    elif user.get("username") is not None:
        cred = f"{user.get('username', '')}:{user.get('password', '')}"
        headers["Authorization"] = (
            "Basic " + base64.b64encode(cred.encode()).decode())

    sslctx = None
    if server.startswith("https"):
        import os

        keep: list = []
        try:
            if cluster.get("insecure-skip-tls-verify"):
                sslctx = ssl.create_default_context()
                sslctx.check_hostname = False
                sslctx.verify_mode = ssl.CERT_NONE
            else:
                ca = _data_or_file(cluster.get("certificate-authority-data"),
                                   cluster.get("certificate-authority"), keep)
                sslctx = ssl.create_default_context(cafile=ca)
            cert = _data_or_file(user.get("client-certificate-data"),
                                 user.get("client-certificate"), keep)
            key = _data_or_file(user.get("client-key-data"),
                                user.get("client-key"), keep)
            if cert and key:
                sslctx.load_cert_chain(cert, key)
        finally:
            # ssl loads files eagerly — inline cert/key material must not
            # outlive this call on disk
            for f in keep:
                f.close()
                try:
                    os.unlink(f.name)
                # best-effort temp-file cleanup; the config loaded
                # kss-analyze: allow(swallowed-exception)
                except OSError:
                    pass
    return server, sslctx, headers


class KubeAPICluster:
    """ObjectStore-shaped client over a real kube-apiserver."""

    def __init__(self, base_url: str | None = None,
                 kubeconfig: str | None = None, context: str | None = None,
                 timeout: float = 10.0, token: str | None = None,
                 extra_paths: dict[str, tuple[str, bool]] | None = None):
        if kubeconfig:
            base_url, sslctx, headers = load_kubeconfig(kubeconfig, context)
        else:
            if not base_url:
                raise ValueError("base_url or kubeconfig required")
            sslctx, headers = None, {}
            if base_url.startswith("https"):
                sslctx = ssl.create_default_context()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.headers = headers
        self.sslctx = sslctx
        self.paths = dict(API_PATHS)
        self.paths.update(extra_paths or {})
        self.resources = {r: (RESOURCES.get(r, (r.capitalize(), ns))[0], ns)
                          for r, (_, ns) in self.paths.items()}
        self._lock = threading.Lock()
        self._watchers: dict[str, list[queue.Queue]] = {}
        self._watch_threads: dict[str, threading.Thread] = {}
        self._watch_stop: dict[str, threading.Event] = {}
        self._rv_counter = 0
        # own lock: _rv_int's synthesized-counter branch (non-integer
        # server rvs) is reached from paths already holding self._lock
        # (the late-subscriber handover replay) — sharing the watch lock
        # deadlocked there
        self._rv_lock = threading.Lock()

    # ---------------- HTTP plumbing -------------------------------------

    def _url(self, resource: str, name: str | None = None,
             namespace: str | None = None, query: dict | None = None) -> str:
        try:
            prefix, namespaced = self.paths[resource]
        except KeyError:
            raise NotFound(f"resource {resource!r} has no API path") from None
        path = prefix
        if namespaced and namespace:
            path += f"/namespaces/{urllib.parse.quote(namespace)}"
        path += f"/{resource}"
        if name:
            path += f"/{urllib.parse.quote(name)}"
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v not in (None, "")})
        return url

    def _request(self, method: str, url: str, body: dict | None = None,
                 timeout: float | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        for k, v in self.headers.items():
            req.add_header(k, v)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self.sslctx)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode(errors="replace")[:300]
            # the detail body is advisory; the HTTPError re-raises typed
            # kss-analyze: allow(swallowed-exception)
            except OSError:
                pass
            if e.code == 404:
                raise NotFound(detail or url) from None
            if e.code == 409:
                raise (AlreadyExists(detail) if "already exists" in detail
                       else Conflict(detail)) from None
            err = ApiError(f"{method} {url}: HTTP {e.code} {detail}")
            err.status = e.code
            raise err from None

    def _json(self, method: str, url: str, body: dict | None = None) -> dict:
        with self._request(method, url, body) as resp:
            return json.loads(resp.read())

    def _rv_int(self, rv_str) -> int:
        try:
            return int(rv_str)
        except (TypeError, ValueError):
            with self._rv_lock:
                self._rv_counter += 1
                return self._rv_counter

    # ---------------- store interface -----------------------------------

    def get(self, resource: str, name: str, namespace: str | None = None,
            **_kw) -> dict:
        namespaced = self.paths.get(resource, ("", False))[1]
        if namespaced and not namespace:
            namespace = "default"  # ObjectStore.get parity
        return self._json("GET", self._url(
            resource, name, namespace if namespaced else None))

    def _list_raw(self, resource: str, namespace: str | None = None,
                  label_selector=None) -> tuple[list[dict], str]:
        sel = _label_selector_str(label_selector)
        data = self._json("GET", self._url(
            resource, namespace=namespace,
            query={"labelSelector": sel} if sel else None))
        items = data.get("items") or []
        kind = data.get("kind", "")
        item_kind = kind[:-4] if kind.endswith("List") else None
        for obj in items:
            # list items omit kind/apiVersion; stamp them the way client-go
            # dynamic listers do so downstream consumers see full objects
            obj.setdefault("kind", item_kind or self.resources[resource][0])
            obj.setdefault("apiVersion", data.get("apiVersion", "v1"))
        rv = ((data.get("metadata") or {}).get("resourceVersion")) or ""
        return items, rv

    def list(self, resource: str, namespace: str | None = None,
             label_selector=None) -> tuple[list[dict], int]:
        items, rv = self._list_raw(resource, namespace, label_selector)
        return items, self._rv_int(rv)

    def create(self, resource: str, obj: dict) -> dict:
        ns = (obj.get("metadata") or {}).get("namespace")
        return self._json("POST", self._url(resource, namespace=ns), obj)

    def update(self, resource: str, obj: dict) -> dict:
        meta = obj.get("metadata") or {}
        return self._json("PUT", self._url(
            resource, meta.get("name", ""), meta.get("namespace")), obj)

    def delete(self, resource: str, name: str,
               namespace: str | None = None) -> None:
        self._json("DELETE", self._url(resource, name, namespace))

    # ---------------- watch ---------------------------------------------

    def watch(self, resource: str, since_rv: int = 0) -> queue.Queue:
        """Subscribe to a server-side watch stream; returns a queue of
        (rv, event_type, obj).  One streaming connection per resource,
        shared by all subscribers; reconnects with the last seen
        resourceVersion (the RetryWatcher behavior, reference:
        resourcewatcher/resourcewatcher.go:127-134) and recovers from
        410 Gone by restarting from the server's current state."""
        if resource not in self.paths:
            raise NotFound(f"resource {resource!r} has no API path")
        q: queue.Queue = queue.Queue()
        buf: queue.Queue | None = None
        with self._lock:
            start_thread = resource not in self._watch_threads
            if start_thread:
                self._watchers.setdefault(resource, []).append(q)
                stop = threading.Event()
                t = threading.Thread(target=self._watch_loop,
                                     args=(resource, stop), daemon=True,
                                     name=f"kubeapi-watch-{resource}")
                self._watch_stop[resource] = stop
                self._watch_threads[resource] = t
                t.start()
            else:
                # the shared loop's initial-state replay already
                # happened; give THIS subscriber its own ADDED replay so
                # every subscriber sees ListAndWatch semantics regardless
                # of arrival order.  The buffer joins the fan-out UNDER
                # THE SAME LOCK HOLD as the _watch_threads check: were it
                # registered in a second acquisition, the last existing
                # subscriber could unwatch() in the window, stopping the
                # loop thread and leaving this subscriber attached to a
                # dead fan-out (one ADDED replay, then silence).  With
                # the buffer already in the subscriber list, unwatch()
                # sees it and keeps the loop alive.
                buf = queue.Queue()
                self._watchers.setdefault(resource, []).append(buf)
        if buf is not None:
            # handover: snapshot ADDEDs first, then buffered events minus
            # the state the snapshot already carries — a buffered event
            # whose resourceVersion EQUALS the listed object's is the very
            # update the list reflected, so replaying it would double-
            # deliver.  The comparison is on the server's EXACT rv
            # strings: resourceVersions are opaque (only equality is
            # defined), and the synthesized _rv_int counters are assigned
            # in arrival order, which is meaningless for non-integer rvs
            # (ADVICE r5 #3).  An event for a key the list doesn't carry
            # (e.g. a DELETE racing the list) always goes through.  The
            # swap buffer -> q is atomic with deliveries (_fanout puts
            # under the lock).
            try:
                items, _ = self._list_raw(resource)
            except BaseException:
                # no orphan subscriber on a failed replay list; unwatch
                # also stops the loop thread if buf was the last one
                self.unwatch(resource, buf)
                raise
            listed: dict = {}
            listed_uid: dict = {}
            for obj in items:
                m = obj.get("metadata") or {}
                k = (m.get("namespace"), m.get("name"))
                listed[k] = m.get("resourceVersion")
                listed_uid[k] = m.get("uid")
            with self._lock:
                subs = self._watchers[resource]
                subs[subs.index(buf)] = q
                for obj in items:
                    orv = (obj.get("metadata") or {}).get("resourceVersion")
                    q.put((self._rv_int(orv), ADDED, obj))
                buffered: list[tuple] = []
                while True:
                    try:
                        buffered.append(buf.get_nowait())
                    # Empty IS the drain's termination, not a failure
                    # kss-analyze: allow(swallowed-exception)
                    except queue.Empty:
                        break
                # the buffer is FIFO per key: the buffered event whose rv
                # EQUALS the listed object's marks the point the snapshot
                # already reflects — drop it and everything before it for
                # that key (older intermediates would regress the
                # subscriber's cache AFTER the newer ADDED), deliver only
                # what came after.  An equal-rv DELETED still goes
                # through: a pre-list delete can't appear in the list, so
                # an equal-rv DELETED is a real post-list deletion.
                # When NO buffered event matches the listed rv (the list
                # raced ahead of the fan-out) opaque rvs are undecidable;
                # events are then DELIVERED — a transiently stale
                # re-delivery self-heals on the next live event, whereas
                # dropping a genuinely newer update loses it forever
                # (the at-least-once bias of the ADVICE r5 #3 contract).
                cut: dict = {}
                for idx, ev in enumerate(buffered):
                    m = (ev[2].get("metadata") or {})
                    k = (m.get("namespace"), m.get("name"))
                    if (k in listed and ev[1] != DELETED
                            and m.get("resourceVersion") == listed[k]):
                        cut[k] = idx + 1
                dead_listed: set = set()
                for idx, ev in enumerate(buffered):
                    m = (ev[2].get("metadata") or {})
                    k = (m.get("namespace"), m.get("name"))
                    if k not in listed:
                        q.put(ev)
                        continue
                    if idx < cut.get(k, 0):
                        continue  # at-or-before the snapshot's state
                    buid, luid = m.get("uid"), listed_uid.get(k)
                    if buid and luid and buid != luid:
                        # a different uid is another incarnation of the
                        # key.  BEFORE the listed incarnation's own
                        # DELETED it can only be an older one (a pre-list
                        # delete can't be listed): stale MODIFIEDs, and a
                        # DELETED that must not remove the live object.
                        # AFTER it, it's a post-list recreate — deliver,
                        # or the subscriber never learns the new object
                        # exists.
                        if k in dead_listed:
                            q.put(ev)
                        continue
                    if ev[1] == DELETED and buid and luid:
                        # the LISTED incarnation died post-list (a
                        # pre-list delete can't appear in the list)
                        dead_listed.add(k)
                    brv, lrv = m.get("resourceVersion"), listed[k]
                    if brv == lrv:
                        if ev[1] != DELETED:
                            continue  # duplicate of the snapshot's state
                    else:
                        try:
                            if int(brv) < int(lrv):
                                continue  # provably older than the snapshot
                        # kss-analyze: allow(swallowed-exception)
                        except (TypeError, ValueError):
                            pass  # opaque rvs: only equality is defined
                    q.put(ev)
        return q

    def unwatch(self, resource: str, q: queue.Queue) -> None:
        with self._lock:
            subs = self._watchers.get(resource, [])
            if q in subs:
                subs.remove(q)
            if not subs and resource in self._watch_threads:
                self._watch_stop[resource].set()
                del self._watch_threads[resource]
                del self._watch_stop[resource]

    def stop(self) -> None:
        with self._lock:
            for stop in self._watch_stop.values():
                stop.set()
            self._watch_threads.clear()
            self._watch_stop.clear()

    def _fanout(self, resource: str, item: tuple) -> None:
        # puts happen UNDER the lock: late-subscriber handover (watch())
        # swaps its buffer for the real queue atomically with respect to
        # deliveries, so no event can race past the swap
        with self._lock:
            for q in self._watchers.get(resource, []):
                q.put(item)

    def _watch_loop(self, resource: str, stop: threading.Event) -> None:
        resume_rv: str | None = None  # server's exact string, for resume
        backoff = 0.5
        while not stop.is_set():
            try:
                if resume_rv is None:
                    # ListAndWatch (client-go reflector semantics): the
                    # initial state arrives as ADDED events, then the
                    # watch resumes from the list's resourceVersion —
                    # the reference's informer-driven recorder records
                    # pre-existing objects exactly this way
                    items, rv_str = self._list_raw(resource)
                    for obj in items:
                        orv = ((obj.get("metadata") or {})
                               .get("resourceVersion"))
                        self._fanout(resource,
                                     (self._rv_int(orv), ADDED, obj))
                    resume_rv = rv_str or "0"
                url = self._url(resource, query={
                    "watch": "true",
                    "resourceVersion": resume_rv,
                    "allowWatchBookmarks": "true",
                })
                # long-lived stream: no read timeout beyond connect
                with self._request("GET", url, timeout=3600) as resp:
                    backoff = 0.5
                    for line in resp:
                        if stop.is_set():
                            return
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        etype = ev.get("type", "")
                        obj = ev.get("object") or {}
                        rv_str = ((obj.get("metadata") or {})
                                  .get("resourceVersion"))
                        if etype == "BOOKMARK":
                            resume_rv = rv_str or resume_rv
                            continue
                        if etype == "ERROR":
                            if (obj.get("code") == 410
                                    or "Gone" in str(obj.get("reason", ""))):
                                resume_rv = None  # expired: restart fresh
                            break
                        mapped = _WATCH_TYPES.get(etype)
                        if mapped is None:
                            continue
                        resume_rv = rv_str or resume_rv
                        if stop.is_set():
                            return  # superseded loop must not double-fan
                        self._fanout(resource,
                                     (self._rv_int(rv_str), mapped, obj))
            except NotFound:
                return  # GVR vanished; nothing to stream
            # transient stream failure: backoff reconnect IS the handling
            # kss-analyze: allow(swallowed-exception)
            except (ApiError, urllib.error.URLError, OSError,
                    json.JSONDecodeError):
                pass  # drop to reconnect
            if stop.wait(backoff):
                return
            backoff = min(backoff * 2, 30.0)


def connect_source(spec: str, timeout: float = 10.0):
    """A source cluster from a CLI/config string.

    - an existing file path -> kubeconfig against a real apiserver
    - a URL serving /apis (API group discovery) -> bare-URL real
      apiserver (KWOK et al. without auth)
    - any other URL -> a simulator server (`cluster.remote.RemoteCluster`)
    """
    import os

    if os.path.isfile(spec):
        return KubeAPICluster(kubeconfig=spec, timeout=timeout)
    probe = KubeAPICluster(base_url=spec, timeout=min(timeout, 5.0))
    try:
        with probe._request("GET", spec.rstrip("/") + "/apis") as resp:
            if (resp.status == 200
                    and "groups" in json.loads(resp.read() or b"{}")):
                return KubeAPICluster(base_url=spec, timeout=timeout)
    # the probe failing IS the signal to fall back to RemoteCluster
    # kss-analyze: allow(swallowed-exception)
    except (ApiError, NotFound, urllib.error.URLError, OSError, ValueError):
        pass
    from .remote import RemoteCluster

    return RemoteCluster(spec, timeout=timeout)
