"""Remote cluster client: CRUD + watch over the simulator HTTP API.

The client-go analogue for this framework: the reference's scheduler,
recorder, and syncer processes talk to a kube-apiserver through client-go
clientsets and dynamic informers (reference:
simulator/cmd/sched-recorder/recorder.go:39-51,
simulator/syncer/syncer.go:53-74).  Here, any out-of-process component
(the standalone scheduler of cmd/scheduler.py, the sched-recorder CLI,
a syncer source) talks to a simulator server's `/api/v1/*` resource CRUD
routes and its `/listwatchresources` push stream through this class,
which implements the same interface as `cluster.store.ObjectStore`
(get/list/create/update/delete/watch/unwatch), so every service that
takes an ObjectStore also works against a remote simulator.

Watch is informer-style: ONE shared streaming connection per client
(the reference's shared informer factory), demultiplexed by kind into
per-resource queues carrying (rv, event_type, obj) tuples — the same
wire tuples ObjectStore.watch delivers.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import urllib.error
import urllib.parse
import urllib.request

from .store import (
    ADDED,
    AlreadyExists,
    ApiError,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    RESOURCES,
    _EVENT_BUFFER,
)

_KIND_TO_RESOURCE = {kind: res for res, (kind, _) in RESOURCES.items()}

_WATCH_EVENTS = {"ADDED": ADDED, "MODIFIED": MODIFIED, "DELETED": DELETED}


def _obj_rv(obj: dict) -> int:
    try:
        return int(((obj.get("metadata") or {}).get("resourceVersion")) or 0)
    except (TypeError, ValueError):
        return 0


class RemoteCluster:
    def __init__(self, base_url: str, timeout: float = 10.0,
                 extra_resources: list[dict] | None = None):
        """extra_resources mirrors ObjectStore's registry: the client of a
        server configured with extraResources declares the same table so
        paths/watch buckets exist for those kinds."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._lock = threading.Lock()
        self.resources: dict[str, tuple[str, bool]] = dict(RESOURCES)
        for spec in extra_resources or []:
            self.resources[spec["resource"]] = (
                spec.get("kind") or spec["resource"].capitalize(),
                bool(spec.get("namespaced", True)))
        self._kind_to_resource = {
            kind: res for res, (kind, _) in self.resources.items()}
        self._watchers: dict[str, list[queue.Queue]] = {r: [] for r in self.resources}
        # recent events per resource, replayed to late-registered watchers
        # so a subscriber added after the stream's initial listing still
        # sees the full state (mirrors ObjectStore's event ring buffer)
        self._events: dict[str, list[tuple[int, str, dict]]] = {r: [] for r in self.resources}
        # highest rv seen per resource — resent as *LastResourceVersion on
        # reconnect so a dropped stream resumes instead of re-listing
        # (the reference RetryWatcher resumes the same way,
        # resourcewatcher.go:127-134)
        self._last_rv: dict[str, int] = {r: 0 for r in self.resources}
        self._stream_thread: threading.Thread | None = None
        self._stream_resp = None
        self._stream_started = False
        self._closed = threading.Event()

    # ----------------------------------------------------------- HTTP

    def _request(self, method: str, path: str, body: dict | None = None) -> dict | None:
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                payload = json.loads(raw) if raw else {}
            except ValueError:
                payload = {}
            msg = payload.get("message") or raw.decode(errors="replace")
            reason = payload.get("reason", "")
            if e.code == 404 or reason == "NotFound":
                raise NotFound(msg) from None
            if reason == "AlreadyExists":
                raise AlreadyExists(msg) from None
            if reason == "Conflict":
                raise Conflict(msg) from None
            err = ApiError(msg)
            err.status = e.code
            raise err from None

    def _obj_path(self, resource: str, name: str, namespace: str | None) -> str:
        _, namespaced = self.resources[resource]
        if namespaced:
            return f"/api/v1/{resource}/{namespace or 'default'}/{name}"
        return f"/api/v1/{resource}/{name}"

    # ----------------------------------------------------------- CRUD

    def get(self, resource: str, name: str, namespace: str | None = None,
            copy_object: bool = True) -> dict:
        # copy_object accepted for in-process-store signature parity; an
        # HTTP GET always materializes a fresh dict
        return self._request("GET", self._obj_path(resource, name, namespace))

    def list(self, resource: str, namespace: str | None = None,
             label_selector: dict | None = None) -> tuple[list[dict], int]:
        path = f"/api/v1/{resource}"
        if namespace:
            path += "?" + urllib.parse.urlencode({"namespace": namespace})
        out = self._request("GET", path) or {}
        items = out.get("items") or []
        if label_selector is not None:
            from ..state.selectors import object_matches_label_selector

            items = [o for o in items
                     if object_matches_label_selector(label_selector, o)]
        try:
            rv = int(out.get("resourceVersion") or 0)
        except ValueError:
            rv = 0
        return items, rv

    def create(self, resource: str, obj: dict, owned: bool = False) -> dict:
        # owned accepted for in-process-store signature parity; a
        # serialized HTTP POST never aliases the caller's dict
        return self._request("POST", f"/api/v1/{resource}", obj)

    def update(self, resource: str, obj: dict, owned: bool = False) -> dict:
        # owned is the in-process store's ownership-transfer hint; a
        # serialized HTTP PUT never aliases the caller's dict, so it is
        # accepted and ignored here
        meta = obj.get("metadata") or {}
        path = self._obj_path(resource, meta.get("name", ""), meta.get("namespace"))
        return self._request("PUT", path, obj)

    def delete(self, resource: str, name: str, namespace: str | None = None) -> None:
        self._request("DELETE", self._obj_path(resource, name, namespace))

    # ----------------------------------------------------------- watch

    def watch(self, resource: str, since_rv: int = 0) -> queue.Queue:
        """Queue of (rv, event_type, obj) for one resource kind, fed by the
        shared stream.  The stream's initial listing arrives as ADDED
        events (the reference watcher emits the same,
        resourcewatcher.go:61-90); events at or below since_rv are
        dropped client-side."""
        if self._closed.is_set():
            raise RuntimeError("RemoteCluster is closed")
        q: queue.Queue = queue.Queue()
        q._since_rv = since_rv  # consulted by the demux thread
        with self._lock:
            for ev in self._events[resource]:
                if ev[0] > since_rv:
                    q.put(ev)
            self._watchers[resource].append(q)
            if not self._stream_started:
                self._stream_started = True
                self._stream_thread = threading.Thread(
                    target=self._stream_loop, daemon=True
                )
                self._stream_thread.start()
        return q

    def unwatch(self, resource: str, q: queue.Queue) -> None:
        with self._lock:
            try:
                self._watchers[resource].remove(q)
            except ValueError:
                pass

    def close(self) -> None:
        self._closed.set()
        self._abort_stream()
        with self._lock:
            for qs in self._watchers.values():
                for q in qs:
                    q.put(None)
                qs.clear()

    def _abort_stream(self) -> None:
        """Unblock the stream thread's in-progress read.  Closing the
        HTTPResponse from another thread deadlocks on the buffered
        reader's lock, so shut the socket down instead — the blocked
        read then returns EOF immediately."""
        import socket as _socket

        resp = self._stream_resp
        if resp is None:
            return
        try:
            resp.fp.raw._sock.shutdown(_socket.SHUT_RDWR)
        except (AttributeError, OSError, ValueError):
            pass

    def _stream_loop(self) -> None:
        from ..services.resourcewatcher import WATCH_PARAMS

        base = self.base_url + "/api/v1/listwatchresources"
        while not self._closed.is_set():
            with self._lock:
                params = {WATCH_PARAMS[r]: str(rv)
                          for r, rv in self._last_rv.items() if rv > 0}
            url = base + ("?" + urllib.parse.urlencode(params) if params else "")
            try:
                resp = urllib.request.urlopen(url, timeout=None)
            except (urllib.error.URLError, OSError):
                if self._closed.wait(0.5):
                    return
                continue
            self._stream_resp = resp
            decoder = json.JSONDecoder()
            buf = ""
            try:
                while not self._closed.is_set():
                    chunk = resp.read1(65536) if hasattr(resp, "read1") else resp.read(4096)
                    if not chunk:
                        break
                    buf += chunk.decode()
                    while buf:
                        buf = buf.lstrip()
                        try:
                            ev, end = decoder.raw_decode(buf)
                        except ValueError:
                            break  # partial object; wait for more bytes
                        buf = buf[end:]
                        self._dispatch(ev)
            except Exception:
                # EOF mid-chunk after an abort, a dropped server, or a
                # malformed event: never let the stream thread die — fall
                # through to reconnect (RetryWatcher semantics)
                pass
            finally:
                try:
                    resp.close()
                except (OSError, http.client.HTTPException):
                    pass
            # reconnect (the reference's RetryWatcher auto-reconnects,
            # resourcewatcher.go:127-134) unless the client closed us
            if self._closed.wait(0.5):
                return

    def _dispatch(self, ev: dict) -> None:
        resource = self._kind_to_resource.get(ev.get("kind") or "")
        event_type = _WATCH_EVENTS.get(ev.get("eventType") or "")
        obj = ev.get("obj")
        if resource is None or event_type is None or obj is None:
            return
        rv = _obj_rv(obj)
        with self._lock:
            if rv > self._last_rv[resource]:
                self._last_rv[resource] = rv
            buf = self._events[resource]
            buf.append((rv, event_type, obj))
            if len(buf) > _EVENT_BUFFER:
                del buf[: len(buf) - _EVENT_BUFFER]
            for q in self._watchers[resource]:
                if rv and rv <= getattr(q, "_since_rv", 0):
                    continue
                q.put((rv, event_type, obj))
