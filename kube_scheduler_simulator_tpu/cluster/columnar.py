"""Columnar node/pod data plane: hot fields in numpy columns.

The dict ObjectStore keeps every object as a full manifest dict; at
100k nodes the per-wave compile re-parses 100k dicts and every listing
materializes 100k Python objects.  This module is the columnar backing
that removes the node axis from Python:

  * `ColumnarNodeBank` / `ColumnarPodBank` — hot fields (name,
    resourceVersion, allocatable/request columns, interned labels,
    taints, pod phase/nodeName) live in numpy arrays, one row per
    object incarnation.  Rows are append-only: a delete tombstones its
    row and a re-create gets a fresh row, so a row index captured by an
    old snapshot can never be re-pointed at a different object.
  * `LazyManifest` — the compat shim: a dict subclass the store keeps
    as the stored object for bulk-loaded rows; it synthesizes its full
    manifest from the bank columns on first real access and behaves
    exactly like the eager dict afterwards.  Consumers that never touch
    a row (the engine's node listings) never pay the synthesis.
  * `NodeColumns` / `PodColumns` — read views the store attaches to
    shared listings (`ColumnarManifestList.columns`): a sorted row-index
    gather over the bank that `state/compile.py` consumes directly,
    vectorized, instead of re-parsing manifests.

Write-path consistency: the manifest (stored dict) is always the source
of truth for rows written through the dict CRUD; the columns are a
synchronized cache (`sync_from_manifest`, guarded by the
`store.columnar_sync` fault seam).  A failed sync marks the row OPAQUE:
readers fall back to the manifest for that row, so a mid-sync fault
degrades to the dict path instead of corrupting the shim.

Snapshot safety: numeric/label/taint columns captured by a compiled
NodeTable are never mutated in place after an update — the bank
replaces whole column arrays copy-on-write (`_cow`), so a previous
wave's table (still pinned by lazy annotation decode) keeps reading the
bytes it captured.
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable

import numpy as np

from ..utils.quantity import parse_cpu_milli, parse_memory_bytes

_BASE_RES = ("cpu", "memory", "ephemeral-storage")
_HOSTNAME = "kubernetes.io/hostname"
_BANK_IDS = itertools.count(1)

DEFAULT_ALLOWED_PODS = 110  # kubelet default max-pods (state/nodes.py)


class LazyManifest(dict):
    """A stored object that synthesizes itself from bank columns on
    first access.  Until filled, the underlying dict storage is EMPTY —
    every dict-protocol entry point below materializes first, so any
    consumer holding one observes exactly the eager manifest's content.

    json.dumps's C encoder walks dict storage directly (bypassing these
    overrides): serialization paths that stream stored objects must call
    `fill()` / `LazyManifest.ensure(obj)` first (StreamWriter.send does;
    copying reads materialize through __deepcopy__)."""

    __slots__ = ("_bank", "_row")

    def __init__(self, bank, row: int):
        super().__init__()
        self._bank = bank
        self._row = row

    def fill(self) -> None:
        bank = self._bank
        if bank is not None:
            # update BEFORE clearing _bank: a concurrent reader must
            # never observe "filled" with empty dict storage (the update
            # of a str-keyed dict is atomic under the GIL; a double fill
            # writes identical content)
            dict.update(self, bank.synthesize(self._row))
            self._bank = None

    @staticmethod
    def ensure(obj):
        """Materialize obj if it is a lazy row; returns obj."""
        if type(obj) is LazyManifest:
            obj.fill()
        return obj

    # -- reads
    def __getitem__(self, k):
        self.fill()
        return dict.__getitem__(self, k)

    def get(self, k, default=None):
        self.fill()
        return dict.get(self, k, default)

    def __contains__(self, k):
        self.fill()
        return dict.__contains__(self, k)

    def __iter__(self):
        self.fill()
        return dict.__iter__(self)

    def __len__(self):
        self.fill()
        return dict.__len__(self)

    def keys(self):
        self.fill()
        return dict.keys(self)

    def values(self):
        self.fill()
        return dict.values(self)

    def items(self):
        self.fill()
        return dict.items(self)

    def __eq__(self, other):
        self.fill()
        if type(other) is LazyManifest:
            other.fill()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)

    __hash__ = None  # dicts are unhashable; keep that

    def __repr__(self):
        self.fill()
        return dict.__repr__(self)

    def copy(self):
        self.fill()
        return dict(self)

    def __copy__(self):
        self.fill()
        return dict(self)

    def __deepcopy__(self, memo):
        import copy as _copy

        self.fill()
        return _copy.deepcopy(dict(self), memo)

    def __reduce__(self):
        self.fill()
        return (dict, (), None, None, iter(dict.items(self)))

    # -- writes (stored objects are replace-on-update, but be safe)
    def __setitem__(self, k, v):
        self.fill()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self.fill()
        dict.__delitem__(self, k)

    def setdefault(self, k, default=None):
        self.fill()
        return dict.setdefault(self, k, default)

    def update(self, *a, **kw):
        self.fill()
        dict.update(self, *a, **kw)

    def pop(self, *a):
        self.fill()
        return dict.pop(self, *a)

    def popitem(self):
        self.fill()
        return dict.popitem(self)


def _grow(arr: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros(cap, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


class _ColumnarBank:
    """Row machinery shared by the node and pod banks."""

    def __init__(self, capacity: int = 64):
        self.bank_id = next(_BANK_IDS)
        cap = max(int(capacity), 1)
        self.n = 0                       # rows allocated (incl. tombstones)
        self.names: list[str] = []
        self.rv = np.zeros(cap, dtype=np.int64)
        self.opaque = np.zeros(cap, dtype=bool)
        self.deleted = np.zeros(cap, dtype=bool)
        self.uid: list[str | None] = []
        self.created: list[str | None] = []
        self.manifests: list[dict | None] = []   # dict-backed rows
        self.row_of: dict[str, int] = {}         # live key -> row
        self.names_version = 0           # bumps on add/delete (membership)
        self.uid_factory: Callable[[], str] | None = None
        self._uid_lock = threading.Lock()
        # label columns: key -> object array (None = absent); replaced
        # copy-on-write on update so captured snapshots stay stable
        self.label_cols: dict[str, np.ndarray] = {}

    # -------------------------------------------------------------- rows
    def _cap(self) -> int:
        return len(self.rv)

    def _ensure_cap(self, need: int) -> None:
        cap = self._cap()
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        self.rv = _grow(self.rv, cap)
        self.opaque = _grow(self.opaque, cap)
        self.deleted = _grow(self.deleted, cap)
        self.label_cols = {
            k: self._grow_obj(col, cap) for k, col in self.label_cols.items()
        }
        self._grow_extra(cap)

    @staticmethod
    def _grow_obj(col: np.ndarray, cap: int) -> np.ndarray:
        out = np.empty(cap, dtype=object)
        out[: len(col)] = col
        return out

    def _grow_extra(self, cap: int) -> None:  # subclass columns
        raise NotImplementedError

    def new_row(self, key: str) -> int:
        """Append a fresh row for `key` (replacing any tombstoned one)."""
        row = self.n
        self._ensure_cap(row + 1)
        self.n += 1
        self.names.append(key)
        self.uid.append(None)
        self.created.append(None)
        self.manifests.append(None)
        self.row_of[key] = row
        self.names_version += 1
        return row

    def bulk_rows(self, names: list[str]) -> int:
        """Append len(names) fresh rows at once (generator fast path);
        returns the first row index.  Column payloads are written by the
        caller directly into the bank arrays."""
        start = self.n
        count = len(names)
        self._ensure_cap(start + count)
        self.n = start + count
        self.names.extend(names)
        self.uid.extend([None] * count)
        self.created.extend([None] * count)
        self.manifests.extend([None] * count)
        row_of = self.row_of
        for i, k in enumerate(names, start):
            row_of[k] = i
        self.names_version += 1
        return start

    def drop(self, key: str) -> None:
        row = self.row_of.pop(key, None)
        if row is not None:
            self.deleted[row] = True
            self.names_version += 1

    # ------------------------------------------------- copy-on-write sets
    def _cow_label(self, key: str, row: int, value) -> None:
        col = self.label_cols.get(key)
        if col is None:
            col = np.empty(self._cap(), dtype=object)
            self.label_cols[key] = col
        else:
            col = col.copy()
            self.label_cols[key] = col
        col[row] = value

    def _set_labels(self, row: int, labels: dict[str, str],
                    cow: bool) -> None:
        if cow:
            for key in self.label_cols:
                if key not in labels and self.label_cols[key][row] is not None:
                    self._cow_label(key, row, None)
            for key, val in labels.items():
                col = self.label_cols.get(key)
                if col is None or col[row] != val:
                    self._cow_label(key, row, val)
        else:
            for key in self.label_cols:
                if key not in labels:
                    self.label_cols[key][row] = None
            for key, val in labels.items():
                col = self.label_cols.get(key)
                if col is None:
                    col = np.empty(self._cap(), dtype=object)
                    self.label_cols[key] = col
                col[row] = val

    # ----------------------------------------------------------- helpers
    def ensure_uid(self, row: int) -> str:
        u = self.uid[row]
        if u is None:
            with self._uid_lock:
                u = self.uid[row]
                if u is None:
                    u = (self.uid_factory or _default_uid)()
                    self.uid[row] = u
        return u

    def row_manifest(self, row: int) -> dict:
        """The authoritative manifest for a row: the stored dict when
        dict-backed, a fresh synthesis otherwise."""
        m = self.manifests[row]
        return m if m is not None else self.synthesize(row)

    def synthesize(self, row: int) -> dict:  # subclass responsibility
        raise NotImplementedError


def _default_uid() -> str:
    import uuid

    return str(uuid.uuid4())


class ColumnarNodeBank(_ColumnarBank):
    """Node hot fields.  Resource columns are registered on demand
    (`res`/`res_present`, parsed base units); `taints` rows are
    immutable lists replaced copy-on-write."""

    def __init__(self, capacity: int = 64):
        super().__init__(capacity)
        cap = self._cap()
        self.res: dict[str, np.ndarray] = {}
        self.res_present: dict[str, np.ndarray] = {}
        self.allowed_pods = np.full(cap, DEFAULT_ALLOWED_PODS, dtype=np.int64)
        self.unschedulable = np.zeros(cap, dtype=bool)
        self.taints: list[list[tuple[str, str, str]]] = []

    def _grow_extra(self, cap: int) -> None:
        self.res = {k: _grow(c, cap) for k, c in self.res.items()}
        self.res_present = {k: _grow(c, cap)
                            for k, c in self.res_present.items()}
        grown = np.full(cap, DEFAULT_ALLOWED_PODS, dtype=np.int64)
        grown[: len(self.allowed_pods)] = self.allowed_pods
        self.allowed_pods = grown
        self.unschedulable = _grow(self.unschedulable, cap)

    def new_row(self, key: str) -> int:
        row = super().new_row(key)
        self.taints.append([])
        return row

    def bulk_rows(self, names: list[str]) -> int:
        start = super().bulk_rows(names)
        self.taints.extend([] for _ in names)
        return start

    def _res_col(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        col = self.res.get(name)
        if col is None:
            col = np.zeros(self._cap(), dtype=np.int64)
            self.res[name] = col
            self.res_present[name] = np.zeros(self._cap(), dtype=bool)
        return col, self.res_present[name]

    def _set_alloc(self, row: int, alloc: dict, cow: bool) -> None:
        names = set()
        for name, value in (alloc or {}).items():
            if name == "pods":
                v = int(float(value))
                if cow:
                    self.allowed_pods = self.allowed_pods.copy()
                self.allowed_pods[row] = v
                continue
            parsed = (parse_cpu_milli(value) if name == "cpu"
                      else parse_memory_bytes(value))
            names.add(name)
            col, present = self._res_col(name)
            if cow:
                col = col.copy()
                present = present.copy()
                self.res[name] = col
                self.res_present[name] = present
            col[row] = parsed
            present[row] = True
        if "pods" not in (alloc or {}):
            if cow and self.allowed_pods[row] != DEFAULT_ALLOWED_PODS:
                self.allowed_pods = self.allowed_pods.copy()
            self.allowed_pods[row] = DEFAULT_ALLOWED_PODS
        for name in self.res:
            if name not in names and self.res_present[name][row]:
                if cow:
                    self.res[name] = self.res[name].copy()
                    self.res_present[name] = self.res_present[name].copy()
                self.res[name][row] = 0
                self.res_present[name][row] = False

    def sync_from_manifest(self, row: int, obj: dict, cow: bool) -> None:
        """Refresh a row's columns from its manifest (the dict write
        path).  Raises on malformed input — the caller marks the row
        opaque and the manifest stays the source of truth."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        self._set_alloc(row, status.get("allocatable") or {}, cow)
        labels = {k: str(v) for k, v in (meta.get("labels") or {}).items()}
        # the implicit hostname label, defaulted exactly where
        # state/nodes.build_node_table defaults it
        labels.setdefault(_HOSTNAME, meta.get("name", self.names[row]))
        self._set_labels(row, labels, cow)
        taints = [
            (t.get("key", ""), str(t.get("value", "")),
             t.get("effect", "NoSchedule"))
            for t in spec.get("taints") or []
        ]
        if cow:
            if taints != self.taints[row]:
                self.taints = list(self.taints)
                self.taints[row] = taints
            self.unschedulable = self.unschedulable.copy()
        else:
            self.taints[row] = taints
        self.unschedulable[row] = bool(spec.get("unschedulable", False))

    # --------------------------------------------------------- synthesis
    def synthesize(self, row: int) -> dict:
        """The full manifest for a generator-created row, byte-identical
        in content to the dict the eager generator + store create path
        would have stored (field insertion order mirrors that path)."""
        name = self.names[row]
        labels: dict[str, str] = {}
        for key, col in self.label_cols.items():
            v = col[row]
            if v is not None:
                labels[key] = v
        meta: dict = {"name": name, "labels": labels}
        meta["uid"] = self.ensure_uid(row)
        meta["resourceVersion"] = str(int(self.rv[row]))
        if self.created[row] is not None:
            meta["creationTimestamp"] = self.created[row]
        spec: dict = {}
        if self.taints[row]:
            spec["taints"] = [
                {"key": k, "value": v, "effect": e}
                for k, v, e in self.taints[row]
            ]
        if self.unschedulable[row]:
            spec["unschedulable"] = True
        alloc: dict = {}
        for rname in _BASE_RES:
            present = self.res_present.get(rname)
            if present is not None and present[row]:
                val = int(self.res[rname][row])
                alloc[rname] = f"{val}m" if rname == "cpu" else str(val)
        for rname, present in self.res_present.items():
            if rname not in _BASE_RES and present[row]:
                alloc[rname] = str(int(self.res[rname][row]))
        alloc["pods"] = str(int(self.allowed_pods[row]))
        return {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": meta,
            "spec": spec,
            "status": {
                "allocatable": alloc,
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def view(self, keys: list[str] | None = None) -> "NodeColumns":
        if keys is None:
            keys = sorted(self.row_of)
        rows = np.fromiter((self.row_of[k] for k in keys),
                           dtype=np.int64, count=len(keys))
        return NodeColumns(self, keys, rows)


class ColumnarPodBank(_ColumnarBank):
    """Pod hot fields: phase/nodeName handles and the parsed resource
    request rows compile_workload gathers by uid instead of re-parsing
    every pod's containers each wave."""

    def __init__(self, capacity: int = 64):
        super().__init__(capacity)
        cap = self._cap()
        self.namespace: list[str] = []
        self.phase = np.empty(cap, dtype=object)
        self.node_name = np.empty(cap, dtype=object)
        self.req: dict[str, np.ndarray] = {}       # resource -> int64 col
        self.nonzero = np.zeros((cap, 2), dtype=np.int64)
        self.row_by_uid: dict[str, int] = {}

    def _grow_extra(self, cap: int) -> None:
        self.phase = self._grow_obj(self.phase, cap)
        self.node_name = self._grow_obj(self.node_name, cap)
        self.req = {k: _grow(c, cap) for k, c in self.req.items()}
        nz = np.zeros((cap, 2), dtype=np.int64)
        nz[: len(self.nonzero)] = self.nonzero
        self.nonzero = nz

    def new_row(self, key: str) -> int:
        row = super().new_row(key)
        self.namespace.append(key.partition("/")[0])
        return row

    def bulk_rows(self, names: list[str]) -> int:
        start = super().bulk_rows(names)
        self.namespace.extend(k.partition("/")[0] for k in names)
        return start

    def ensure_uid(self, row: int) -> str:
        u = self.uid[row]
        if u is None:
            u = super().ensure_uid(row)
            self.row_by_uid[u] = row
        return u

    def _req_col(self, name: str) -> np.ndarray:
        col = self.req.get(name)
        if col is None:
            col = np.zeros(self._cap(), dtype=np.int64)
            self.req[name] = col
        return col

    def sync_from_manifest(self, row: int, obj: dict, cow: bool) -> None:
        """Refresh pod hot columns.  The request row is parsed ONCE here
        (same math as state/resources.pod_resource_request, over the
        pod's own resource names) and gathered per wave-schema column at
        compile time.  Raises on malformed input — caller marks opaque."""
        from ..state.resources import ResourceSchema, pod_resource_request

        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        uid = meta.get("uid")
        if uid:
            old = self.uid[row]
            if old and old != uid:
                self.row_by_uid.pop(old, None)
            self.uid[row] = uid
            self.row_by_uid[uid] = row
        self.phase[row] = status.get("phase")
        self.node_name[row] = spec.get("nodeName")
        ext: set[str] = set()
        for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
            for rname in ((c.get("resources") or {}).get("requests")) or {}:
                if rname not in _BASE_RES and rname != "pods":
                    ext.add(rname)
        for rname in spec.get("overhead") or {}:
            if rname not in _BASE_RES and rname != "pods":
                ext.add(rname)
        schema = ResourceSchema(tuple(sorted(ext)))
        total, nonzero = pod_resource_request(obj, schema)
        for j, rname in enumerate(schema.columns):
            self._req_col(rname)[row] = total[j]
        for rname in self.req:
            if rname not in schema.columns:
                self.req[rname][row] = 0
        self.nonzero[row] = nonzero
        labels = {k: str(v) for k, v in (meta.get("labels") or {}).items()}
        self._set_labels(row, labels, cow=False)

    def request_row(self, uid: str, columns: tuple[str, ...]):
        """(total[R], nonzero[2]) for a synced pod, or None when the row
        is missing/opaque (caller falls back to the per-pod parse)."""
        row = self.row_by_uid.get(uid)
        if row is None or self.opaque[row] or self.deleted[row]:
            return None
        total = np.zeros(len(columns), dtype=np.int64)
        for j, rname in enumerate(columns):
            col = self.req.get(rname)
            if col is not None:
                total[j] = col[row]
        return total, self.nonzero[row].copy()

    # --------------------------------------------------------- synthesis
    def synthesize(self, row: int) -> dict:
        name = self.names[row].partition("/")[2]
        labels: dict[str, str] = {}
        for key, col in self.label_cols.items():
            v = col[row]
            if v is not None:
                labels[key] = v
        meta: dict = {
            "name": name,
            "namespace": self.namespace[row],
        }
        if labels:
            meta["labels"] = labels
        meta["uid"] = self.ensure_uid(row)
        meta["resourceVersion"] = str(int(self.rv[row]))
        if self.created[row] is not None:
            meta["creationTimestamp"] = self.created[row]
        cpu = int(self._req_col("cpu")[row])
        mem = int(self._req_col("memory")[row])
        spec: dict = {
            "containers": [{
                "name": "main",
                "image": "registry.k8s.io/pause:3.9",
                "resources": {"requests": {"cpu": f"{cpu}m",
                                           "memory": str(mem)}},
            }],
        }
        aff = self.synth_affinity(row)
        if aff is not None:
            spec["affinity"] = aff
        obj = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": meta,
            "spec": spec,
        }
        if self.phase[row] or self.node_name[row]:
            if self.node_name[row]:
                spec["nodeName"] = self.node_name[row]
            if self.phase[row]:
                obj["status"] = {"phase": self.phase[row]}
        return obj

    # required-only nodeAffinity templates for generated pods: code 0 =
    # none; codes 1..K index `affinity_templates` (models/workloads.py
    # registers them); stored per row so synthesis is exact
    affinity_templates: list[dict] = []

    def synth_affinity(self, row: int) -> dict | None:
        code_col = getattr(self, "_affinity_code", None)
        if code_col is None:
            return None
        code = int(code_col[row])
        if code <= 0 or code > len(self.affinity_templates):
            return None
        import copy as _copy

        return _copy.deepcopy(self.affinity_templates[code - 1])

    def set_affinity_codes(self, codes: np.ndarray,
                           templates: list[dict]) -> None:
        self._affinity_code = codes.astype(np.int64)
        self.affinity_templates = list(templates)

    def view(self, keys: list[str] | None = None) -> "PodColumns":
        if keys is None:
            keys = sorted(self.row_of)
        rows = np.fromiter((self.row_of[k] for k in keys),
                           dtype=np.int64, count=len(keys))
        return PodColumns(self, keys, rows)


class NodeColumns:
    """Sorted read view over a ColumnarNodeBank: the `.columns` handle
    compile_workload consumes.  Gathers are vectorized; captured column
    references stay valid because bank updates are copy-on-write."""

    def __init__(self, bank: ColumnarNodeBank, keys: list[str],
                 rows: np.ndarray):
        self.bank = bank
        self.names = keys
        self.rows = rows
        self.rv = bank.rv[rows] if len(rows) else np.zeros(0, np.int64)
        self._label_cols = dict(bank.label_cols)
        self._taints = bank.taints

    @property
    def n(self) -> int:
        return len(self.names)

    def identity(self) -> tuple:
        """Cheap wave-to-wave table identity: same bank + same
        membership/order + same resourceVersions => same node table."""
        return ("columnar", self.bank.bank_id, self.bank.names_version,
                self.rv.tobytes())

    def opaque_positions(self) -> np.ndarray:
        """View positions whose columns are unreliable (sync faults):
        readers re-parse those rows' manifests."""
        if not len(self.rows):
            return np.zeros(0, dtype=np.int64)
        return np.flatnonzero(self.bank.opaque[self.rows])

    def row_manifest(self, pos: int) -> dict:
        return self.bank.row_manifest(int(self.rows[pos]))

    def extended_names(self) -> set[str]:
        """Exact extended-resource names present on THIS view's rows —
        matches ResourceSchema.discover over the materialized dicts."""
        out: set[str] = set()
        for rname, present in self.bank.res_present.items():
            if rname in _BASE_RES:
                continue
            if len(self.rows) and bool(present[self.rows].any()):
                out.add(rname)
        for pos in self.opaque_positions():
            alloc = ((self.row_manifest(int(pos)).get("status") or {})
                     .get("allocatable")) or {}
            for rname in alloc:
                if rname not in _BASE_RES and rname != "pods":
                    out.add(rname)
        return out

    def alloc_matrix(self, columns: tuple[str, ...]) -> np.ndarray:
        """[N, R] int64 allocatable in schema column order."""
        out = np.zeros((len(self.rows), len(columns)), dtype=np.int64)
        for j, rname in enumerate(columns):
            col = self.bank.res.get(rname)
            if col is not None:
                out[:, j] = col[self.rows]
        return out

    def allowed_pods(self) -> np.ndarray:
        return self.bank.allowed_pods[self.rows]

    def unschedulable(self) -> np.ndarray:
        return self.bank.unschedulable[self.rows].copy()

    def label_rows(self) -> "_LabelRows":
        return _LabelRows(self._label_cols, self.rows, self.names)

    def taint_rows(self) -> "_TaintRows":
        return _TaintRows(self._taints, self.rows)


class PodColumns:
    """Sorted read view over a ColumnarPodBank."""

    def __init__(self, bank: ColumnarPodBank, keys: list[str],
                 rows: np.ndarray):
        self.bank = bank
        self.keys = keys
        self.rows = rows

    @property
    def n(self) -> int:
        return len(self.keys)

    def request_row(self, uid: str, columns: tuple[str, ...]):
        return self.bank.request_row(uid, columns)


class _LabelRows:
    """Sequence of per-node label dicts synthesized on demand from the
    captured label columns — NodeTable.labels without N dict objects.
    `column(key)` is the LabelIndex fast path: the captured column
    gathered once, no per-row Python."""

    __slots__ = ("_cols", "_rows", "_names", "_gathered", "_overrides")

    def __init__(self, cols: dict[str, np.ndarray], rows: np.ndarray,
                 names: list[str], overrides: dict[int, dict] | None = None):
        self._cols = cols
        self._rows = rows
        self._names = names
        self._gathered: dict[str, np.ndarray] = {}
        self._overrides = overrides or {}

    def __len__(self) -> int:
        return len(self._rows)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ov = self._overrides.get(int(i))
        if ov is not None:
            return ov
        row = int(self._rows[i])
        out: dict[str, str] = {}
        for key, col in self._cols.items():
            v = col[row]
            if v is not None:
                out[key] = v
        out.setdefault(_HOSTNAME, self._names[i])
        return out

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def column(self, key: str) -> np.ndarray:
        g = self._gathered.get(key)
        if g is not None:
            return g
        col = self._cols.get(key)
        if col is None:
            g = np.full(len(self._rows), None, dtype=object)
        else:
            g = col[self._rows]
        if key == _HOSTNAME:
            missing = np.equal(g, None)
            if missing.any():
                g = g.copy()
                g[missing] = np.asarray(self._names,
                                        dtype=object)[missing]
        for i, ov in self._overrides.items():
            if g is self._cols.get(key):
                g = g.copy()
            g[i] = ov.get(key)
            if key == _HOSTNAME and g[i] is None:
                g[i] = self._names[i]
        self._gathered[key] = g
        return g

    def with_overrides(self, overrides: dict[int, dict]) -> "_LabelRows":
        merged = dict(self._overrides)
        merged.update(overrides)
        return _LabelRows(self._cols, self._rows, self._names, merged)


class _TaintRows:
    """Sequence view of per-node taint lists (shared immutable rows)."""

    __slots__ = ("_pool", "_rows", "_overrides")

    def __init__(self, pool: list, rows: np.ndarray,
                 overrides: dict[int, list] | None = None):
        self._pool = pool
        self._rows = rows
        self._overrides = overrides or {}

    def __len__(self) -> int:
        return len(self._rows)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ov = self._overrides.get(int(i))
        if ov is not None:
            return ov
        return self._pool[int(self._rows[i])]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def with_overrides(self, overrides: dict[int, list]) -> "_TaintRows":
        merged = dict(self._overrides)
        merged.update(overrides)
        return _TaintRows(self._pool, self._rows, merged)


class ColumnarManifestList(list):
    """A shared listing that carries its columnar view: list element i
    is the stored object for `columns` row position i (lazy until
    touched).  `compile_workload` detects `.columns` and never touches
    the elements; dict consumers index/iterate as usual."""

    __slots__ = ("columns",)

    def __init__(self, items, columns):
        super().__init__(items)
        self.columns = columns
