"""In-memory cluster object store — the KWOK/etcd analogue.

The reference runs against a KWOK fake cluster (etcd + kube-apiserver with
no kubelets, reference: compose.yml:53-66, kwok.yaml:1-12) and talks to it
via client-go.  This store replaces that whole external dependency with an
in-process structure offering the same contract the simulator's services
rely on:

  * objects are unstructured dicts keyed by (resource, namespace/name);
  * a single monotonically increasing resourceVersion (etcd revision
    analogue) stamped on every write;
  * optimistic concurrency: update with a stale metadata.resourceVersion
    fails with Conflict — required for the reflector's conflict-retry path
    (reference: storereflector.go:136-151);
  * list + watch: watch(resource, since_rv) replays buffered events after
    since_rv then streams live ones (RetryWatcher analogue, reference:
    resourcewatcher/resourcewatcher.go:106-134);
  * dump()/restore() of the full keyspace — the etcd snapshot/restore the
    reset service uses (reference: reset/reset.go:32-85).

Thread-safe; watch queues are unbounded stdlib queues.
"""

from __future__ import annotations

import copy
import itertools
import queue
import threading
import time
import uuid

import numpy as np

from .columnar import (
    ColumnarManifestList,
    ColumnarNodeBank,
    ColumnarPodBank,
    LazyManifest,
)
from ..utils.env import env_bool
from ..utils.faults import fault_point

# resource name -> (kind, namespaced).  The first 7 are the kinds the
# reference simulator watches/records/syncs (reference:
# recorder/recorder.go:45-53 DefaultGVRs — see DEFAULT_GVRS below);
# PodDisruptionBudgets are additionally storable so PDB-aware preemption
# can honor them (the real scheduler reads PDBs from the apiserver even
# though the simulator never syncs them).
RESOURCES: dict[str, tuple[str, bool]] = {
    "namespaces": ("Namespace", False),
    "priorityclasses": ("PriorityClass", False),
    "storageclasses": ("StorageClass", False),
    "persistentvolumeclaims": ("PersistentVolumeClaim", True),
    "nodes": ("Node", False),
    "persistentvolumes": ("PersistentVolume", False),
    "pods": ("Pod", True),
    "poddisruptionbudgets": ("PodDisruptionBudget", True),
}

# the reference's 7 DefaultGVRs — the watch/record/sync surface
DEFAULT_GVRS = [
    "namespaces", "priorityclasses", "storageclasses",
    "persistentvolumeclaims", "nodes", "persistentvolumes", "pods",
]

API_VERSIONS = {
    "priorityclasses": "scheduling.k8s.io/v1",
    "storageclasses": "storage.k8s.io/v1",
    "poddisruptionbudgets": "policy/v1",
}

ADDED, MODIFIED, DELETED = "ADDED", "MODIFIED", "DELETED"

_EVENT_BUFFER = 4096  # per-resource ring buffer for watch replay

# resources with a columnar hot-field backing (cluster/columnar.py)
_COLUMNAR_BANKS = {"nodes": ColumnarNodeBank, "pods": ColumnarPodBank}


def _new_uid() -> str:
    return str(uuid.uuid4())


class ApiError(Exception):
    status = 500
    reason = "InternalError"

    def __init__(self, msg: str):
        super().__init__(msg)
        self.message = msg


class NotFound(ApiError):
    status = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    status = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    status = 409
    reason = "Conflict"


def obj_key(obj: dict, namespaced: bool) -> str:
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    if namespaced:
        return f"{meta.get('namespace') or 'default'}/{name}"
    return name


class ObjectStore:
    def __init__(self, extra_resources: list[dict] | None = None):
        """extra_resources: declarative GVR registrations beyond the
        built-in table — the RESTMapper analogue (the reference's
        resourceapplier works on any GVK via dynamic client + RESTMapper,
        resourceapplier.go:91-194,268-276).  Each entry:
        {"resource": plural, "kind": Kind, "namespaced": bool,
        "apiVersion": group/version} — from config extraResources or
        register_resource()."""
        self._lock = threading.RLock()
        self.resources: dict[str, tuple[str, bool]] = dict(RESOURCES)
        self.api_versions: dict[str, str] = dict(API_VERSIONS)
        self._objects: dict[str, dict[str, dict]] = {r: {} for r in RESOURCES}
        self._rv = itertools.count(1)
        self._last_rv = 0
        self._events: dict[str, list[tuple[int, str, dict]]] = {r: [] for r in RESOURCES}
        self._watchers: dict[str, list[queue.Queue]] = {r: [] for r in RESOURCES}
        # read hooks (store/lazy.py LazyReflections): deferred-annotation
        # materializers drained before copying reads return, so API
        # consumers observe exactly the eager write-back's bytes while
        # the engine's shared-manifest fast paths (copy_object(s)=False)
        # stay off the decode
        self._read_hooks: list = []
        # columnar data plane (cluster/columnar.py): hot fields of
        # nodes/pods mirrored into numpy banks on every write (guarded
        # by the store.columnar_sync fault seam; a failed sync marks the
        # row opaque and the manifest stays authoritative).  Listings
        # carry the bank view as ColumnarManifestList.columns so the
        # compile path reads columns instead of re-parsing manifests.
        # KSS_TPU_COLUMNAR=0 pins the pure dict baseline.
        self._columnar = env_bool("KSS_TPU_COLUMNAR", True)
        self._banks: dict = {}
        if self._columnar:
            for resource, factory in _COLUMNAR_BANKS.items():
                bank = factory()
                bank.uid_factory = _new_uid
                self._banks[resource] = bank
        # per-resource write counters keying the sorted-listing cache
        self._res_version: dict[str, int] = {}
        self._list_cache: dict[str, tuple] = {}
        for spec in extra_resources or []:
            self.register_resource(
                spec["resource"], spec.get("kind") or spec["resource"].capitalize(),
                namespaced=bool(spec.get("namespaced", True)),
                api_version=spec.get("apiVersion") or "v1",
            )

    def register_resource(self, resource: str, kind: str,
                          namespaced: bool = True,
                          api_version: str = "v1") -> None:
        """Register an additional resource kind so CRUD/watch/dump/restore
        (and every service built on them: applier, importer, syncer,
        recorder, watcher, snapshot) carry it.  Idempotent."""
        with self._lock:
            if resource not in self.resources:
                self._objects[resource] = {}
                self._events[resource] = []
                self._watchers[resource] = []
            self.resources[resource] = (kind, namespaced)
            if api_version and api_version != "v1":
                self.api_versions[resource] = api_version

    # ----------------------------------------------------------- read hooks

    def add_read_hook(self, hook) -> None:
        """Register a deferred-annotation materializer.  `hook.flush(
        resource, name, namespace)` runs BEFORE copying reads (get with
        copy_object=True, list with copy_objects=True, dump) return —
        with no store lock held, so a hook may write back through the
        normal update path; name=None flushes the whole resource,
        resource=None flushes everything.  `hook.discard(resource,
        name, namespace)` drops pending state for deleted/reset
        objects.  Idempotent per hook object."""
        with self._lock:
            if hook not in self._read_hooks:
                self._read_hooks.append(hook)

    def remove_read_hook(self, hook) -> None:
        with self._lock:
            try:
                self._read_hooks.remove(hook)
            except ValueError:
                pass

    def materialize_reads(self, resource: str | None = None,
                          name: str | None = None,
                          namespace: str | None = None) -> None:
        """Drain registered read hooks (no-op without hooks or pending
        state) — the transparent-read barrier copying reads run, also
        callable directly by consumers of the shared-manifest fast
        paths (snapshot export, the HTTP watch stream) that need the
        eager bytes without paying per-object deep copies.

        Also fills LAZY columnar rows in scope: consumers that hand
        shared manifests to C-level serializers (json.dumps walks dict
        storage, bypassing LazyManifest's overrides) call this first and
        then observe full bytes."""
        for hook in tuple(self._read_hooks):
            hook.flush(resource, name, namespace)
        self._fill_lazy(resource, name, namespace)

    def _fill_lazy(self, resource: str | None, name: str | None = None,
                   namespace: str | None = None) -> None:
        for res, bank in self._banks.items():
            if resource is not None and res != resource:
                continue
            objs = self._objects.get(res)
            if not objs:
                continue
            if name is not None:
                _, namespaced = self.resources[res]
                key = (f"{namespace or 'default'}/{name}"
                       if namespaced else name)
                LazyManifest.ensure(objs.get(key))
            else:
                with self._lock:
                    vals = list(objs.values())
                for obj in vals:
                    LazyManifest.ensure(obj)

    def _discard_hooks(self, resource: str | None, name: str | None = None,
                       namespace: str | None = None) -> None:
        for hook in tuple(self._read_hooks):
            hook.discard(resource, name, namespace)

    # ----------------------------------------------------------- columnar

    def _bump(self, resource: str) -> None:
        """Invalidate the sorted-listing cache for resource (lock held)."""
        self._res_version[resource] = self._res_version.get(resource, 0) + 1

    def _columnar_sync(self, resource: str, op: str, key: str,
                       obj: dict | None) -> None:
        """Mirror a write into the columnar bank (lock held).  Never
        raises: a sync failure (including an injected store.columnar_sync
        fault) marks the row OPAQUE, and every columnar reader falls back
        to the manifest for opaque rows — the shim stays consistent."""
        bank = self._banks.get(resource)
        if bank is None:
            return
        if op == "delete":
            bank.drop(key)
            return
        row = None
        try:
            fault_point("store.columnar_sync")
            row = bank.new_row(key) if op == "create" else bank.row_of[key]
            bank.manifests[row] = obj
            meta = obj.get("metadata") or {}
            bank.rv[row] = int(meta.get("resourceVersion") or 0)
            uid = meta.get("uid")
            if uid:
                bank.uid[row] = uid
                by_uid = getattr(bank, "row_by_uid", None)
                if by_uid is not None:
                    by_uid[uid] = row
            bank.created[row] = meta.get("creationTimestamp")
            bank.sync_from_manifest(row, obj, cow=(op != "create"))
            bank.opaque[row] = False
        except Exception:
            if row is None:
                row = bank.row_of.get(key)
                if row is None:
                    row = bank.new_row(key)
            bank.manifests[row] = obj
            bank.opaque[row] = True
            try:
                bank.rv[row] = int(
                    (obj.get("metadata") or {}).get("resourceVersion") or 0)
            except Exception:
                pass

    def _list_columns(self, resource: str, keys: list[str]):
        bank = self._banks.get(resource)
        if bank is None:
            return None
        try:
            return bank.view(keys)
        except KeyError:
            return None  # bank coverage hole: dict listing only

    def load_columnar(self, resource: str, bank) -> int:
        """Bulk-attach a generator-built bank (make_nodes_columnar /
        make_pods_columnar) as `resource`'s population: rows become LAZY
        stored objects that synthesize their manifest from the bank on
        first read, with the same rv/uid/creationTimestamp stamping and
        watch events the per-object create path produces — n objects for
        one lock hold and zero manifest dicts until someone looks.
        Requires an empty resource.  Returns the number of rows loaded.

        Pods fall back to per-row create() when a globalDefault
        PriorityClass exists (priority admission must inspect each pod).
        """
        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        if resource not in _COLUMNAR_BANKS:
            raise ApiError(f"no columnar backing for resource {resource}")
        slow = not self._columnar
        if resource == "pods" and not slow:
            with self._lock:
                slow = any(pc.get("globalDefault") for pc in
                           self._objects["priorityclasses"].values())
        if slow:
            n = bank.n
            for row in range(n):
                self.create(resource, bank.synthesize(row), owned=True)
            return n
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with self._lock:
            if self._objects[resource]:
                raise ApiError(
                    f"load_columnar requires an empty {resource} keyspace")
            n = bank.n
            first = next(self._rv)
            self._rv = itertools.count(first + n)
            self._last_rv = first + n - 1
            bank.rv[:n] = np.arange(first, first + n, dtype=np.int64)
            bank.created[:n] = [ts] * n
            bank.uid_factory = _new_uid
            self._banks[resource] = bank
            objs = self._objects[resource]
            events = []
            for key, row in bank.row_of.items():
                lm = LazyManifest(bank, row)
                objs[key] = lm
                events.append((int(bank.rv[row]), ADDED, lm))
            events.sort(key=lambda ev: ev[0])
            if self._watchers[resource]:
                for ev in events:
                    for q in self._watchers[resource]:
                        q.put(ev)
            buf = self._events[resource]
            buf.extend(events[-_EVENT_BUFFER:])
            if len(buf) > _EVENT_BUFFER:
                del buf[: len(buf) - _EVENT_BUFFER]
            self._bump(resource)
            return n

    # ----------------------------------------------------------- helpers

    def _next_rv(self) -> int:
        self._last_rv = next(self._rv)
        return self._last_rv

    def _notify(self, resource: str, event_type: str, obj: dict, rv: int):
        ev = (rv, event_type, obj)
        buf = self._events[resource]
        buf.append(ev)
        if len(buf) > _EVENT_BUFFER:
            del buf[: len(buf) - _EVENT_BUFFER]
        for q in self._watchers[resource]:
            q.put(ev)

    def _stamp_kind(self, resource: str, obj: dict):
        kind, _ = self.resources[resource]
        obj.setdefault("kind", kind)
        obj.setdefault("apiVersion", self.api_versions.get(resource, "v1"))

    # the apiserver's built-in PriorityClasses (scheduling.k8s.io)
    _BUILTIN_PRIORITY_CLASSES = {
        "system-cluster-critical": 2000000000,
        "system-node-critical": 2000001000,
    }

    def _admit_pod_priority(self, obj: dict) -> None:
        """Priority admission analogue: resolve .spec.priority from
        priorityClassName (or the globalDefault class) at create time,
        the way the reference's kube-apiserver does for pods the
        simulator imports or users post.  Caller holds the lock."""
        spec = obj.setdefault("spec", {})
        if spec.get("priority") is not None:
            return
        name = spec.get("priorityClassName") or ""
        if name:
            if name in self._BUILTIN_PRIORITY_CLASSES:
                spec["priority"] = self._BUILTIN_PRIORITY_CLASSES[name]
                return
            pc = self._objects["priorityclasses"].get(name)
            if pc is None:
                e = ApiError(f'no PriorityClass with name "{name}" was found')
                e.status = 400
                e.reason = "Invalid"
                raise e
            spec["priority"] = int(pc.get("value") or 0)
            return
        for pc in self._objects["priorityclasses"].values():
            if pc.get("globalDefault"):
                spec["priorityClassName"] = pc["metadata"]["name"]
                spec["priority"] = int(pc.get("value") or 0)
                return

    # ----------------------------------------------------------- CRUD

    def create(self, resource: str, obj: dict, owned: bool = False) -> dict:
        """owned=True transfers ownership of obj (no entry copy) — see
        update()."""
        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        _, namespaced = self.resources[resource]
        if not owned:
            obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        if namespaced:
            meta.setdefault("namespace", "default")
        key = obj_key(obj, namespaced)
        with self._lock:
            if key in self._objects[resource]:
                raise AlreadyExists(f"{resource} \"{key}\" already exists")
            if resource == "pods":
                self._admit_pod_priority(obj)
            rv = self._next_rv()
            meta["uid"] = meta.get("uid") or str(uuid.uuid4())
            meta["resourceVersion"] = str(rv)
            meta.setdefault(
                "creationTimestamp",
                time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            )
            self._stamp_kind(resource, obj)
            self._objects[resource][key] = obj
            self._columnar_sync(resource, "create", key, obj)
            self._bump(resource)
            # events and the return share the stored dict (see update():
            # stored objects are replaced, never mutated in place)
            self._notify(resource, ADDED, obj, rv)
            return obj

    def update(self, resource: str, obj: dict, owned: bool = False) -> dict:
        """owned=True transfers ownership of obj to the store (no entry
        copy) — the caller MUST NOT touch obj afterwards.  The return
        value and watch events share the stored dict: stored objects are
        never mutated in place (updates REPLACE them), and consumers must
        not mutate what they receive (the informer-cache contract, same
        as list_shared)."""
        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        _, namespaced = self.resources[resource]
        if not owned:
            obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        if namespaced:
            meta.setdefault("namespace", "default")
        key = obj_key(obj, namespaced)
        with self._lock:
            cur = self._objects[resource].get(key)
            if cur is None:
                raise NotFound(f"{resource} \"{key}\" not found")
            # a superseded lazy row must capture its pre-update bytes
            # BEFORE the bank columns move on (watch events/readers may
            # still hold it)
            LazyManifest.ensure(cur)
            sent_rv = meta.get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"Operation cannot be fulfilled on {resource} \"{key}\": "
                    "the object has been modified"
                )
            if resource == "pods":
                self._validate_pod_update(key, cur, obj)
            rv = self._next_rv()
            meta["uid"] = cur["metadata"]["uid"]
            meta["resourceVersion"] = str(rv)
            meta.setdefault("creationTimestamp", cur["metadata"].get("creationTimestamp"))
            self._stamp_kind(resource, obj)
            self._objects[resource][key] = obj
            self._columnar_sync(resource, "update", key, obj)
            self._bump(resource)
            self._notify(resource, MODIFIED, obj, rv)
            return obj

    def delete(self, resource: str, name: str, namespace: str | None = None) -> None:
        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        _, namespaced = self.resources[resource]
        key = f"{namespace or 'default'}/{name}" if namespaced else name
        with self._lock:
            cur = self._objects[resource].pop(key, None)
            if cur is None:
                raise NotFound(f"{resource} \"{key}\" not found")
            rv = self._next_rv()
            # an unfilled lazy row stays synthesizable after drop() (it
            # holds its own bank ref and tombstoned rows keep their
            # column bytes), so no eager fill here
            self._columnar_sync(resource, "delete", key, None)
            self._bump(resource)
            self._notify(resource, DELETED, cur, rv)  # popped: share freely
        if self._read_hooks:
            # a deleted object's deferred annotations are unobservable:
            # drop them (outside the lock) so they stop pinning the
            # wave's replay buffers
            self._discard_hooks(resource, name, namespace)

    def get(self, resource: str, name: str, namespace: str | None = None,
            copy_object: bool = True) -> dict:
        """copy_object=False returns the STORED object (no deep copy) —
        the read-only fast path; the caller must not mutate it (writers
        build a new object copy-on-write and update(owned=True))."""
        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        _, namespaced = self.resources[resource]
        if copy_object and self._read_hooks:
            # transparent lazy-annotation materialization (store/lazy.py):
            # runs before the lock so the hook's write-back can take it
            self.materialize_reads(resource, name, namespace)
        key = f"{namespace or 'default'}/{name}" if namespaced else name
        with self._lock:
            cur = self._objects[resource].get(key)
            if cur is None:
                raise NotFound(f"{resource} \"{key}\" not found")
        # deep copy OUTSIDE the lock hold: stored objects are replaced,
        # never mutated in place (the update() contract), so the
        # reference grabbed under the lock is an immutable snapshot and
        # the O(object) copy must not serialize every other store user
        return copy.deepcopy(cur) if copy_object else cur

    def list(self, resource: str, namespace: str | None = None,
             label_selector: dict | None = None,
             copy_objects: bool = True) -> tuple[list[dict], int]:
        """-> (items, list resourceVersion).

        copy_objects=False returns the STORED objects without deep copies
        — a read-only fast path for the scheduling engine, whose per-wave
        listings of 10k annotated pods otherwise spend more time in
        deepcopy than in scheduling (callers MUST NOT mutate the returned
        manifests; upstream informer-cache objects carry the same
        contract)."""
        from ..state.selectors import object_matches_label_selector

        if copy_objects and self._read_hooks:
            # copying lists are the API-read surface: drain deferred
            # annotations for the whole resource first (the engine's
            # per-wave listings use copy_objects=False and stay lazy)
            self.materialize_reads(resource)
        with self._lock:
            if resource not in self.resources:
                raise NotFound(f"unknown resource {resource}")
            _, namespaced = self.resources[resource]
            # sorted-listing cache keyed on the per-resource write
            # counter: successive waves over an unchanged keyspace skip
            # the O(N log N) sort AND the columnar view rebuild
            ver = self._res_version.get(resource, 0)
            entry = self._list_cache.get(resource)
            if entry is not None and entry[0] == ver:
                _, keys, shared, cols = entry
            else:
                pairs = sorted(self._objects[resource].items())
                keys = [k for k, _ in pairs]
                shared = [o for _, o in pairs]
                cols = self._list_columns(resource, keys)
                self._list_cache[resource] = (ver, keys, shared, cols)
            if namespace is None and label_selector is None:
                # fresh list object per call (callers may mutate the
                # LIST; the elements stay shared as documented)
                items = (ColumnarManifestList(shared, cols)
                         if cols is not None else list(shared))
            else:
                items = []
                for key, obj in zip(keys, shared):
                    if namespace:
                        # namespaced keys carry the namespace — keep
                        # lazy rows unmaterialized on this filter
                        ns = (key.partition("/")[0] if namespaced else
                              ((obj.get("metadata") or {}).get("namespace")
                               or "default"))
                        if ns != namespace:
                            continue
                    if label_selector is not None and not \
                            object_matches_label_selector(label_selector, obj):
                        continue
                    items.append(obj)
            rv = self._last_rv
        if copy_objects:
            # the listing snapshot is the references; the O(N x object)
            # deep copies run outside the lock hold (stored objects are
            # replace-on-update, so the refs cannot change underneath) —
            # a 10k-pod copying list() must not stall writers/watchers
            items = [copy.deepcopy(obj) for obj in items]
        return items, rv

    def _validate_pod_update(self, key: str, cur: dict, obj: dict) -> None:
        """apiserver validation: spec.nodeName is write-once (only the
        empty->set transition of binding is allowed); this is what
        actually protects the simulator's placement authority from synced
        source-cluster updates."""
        cur_node = (cur.get("spec") or {}).get("nodeName") or ""
        new_node = (obj.get("spec") or {}).get("nodeName") or ""
        if cur_node and new_node != cur_node:
            e = ApiError(
                f'Pod "{key}" is invalid: spec: Forbidden: pod '
                "updates may not change fields other than allowed ones "
                f"(spec.nodeName {cur_node!r} -> {new_node!r})"
            )
            e.status = 422
            e.reason = "Invalid"
            raise e

    def apply_batch(self, resource: str, mutations) -> int:
        """Apply many read-modify-write updates under ONE lock hold — the
        scheduling engine's wave-commit write path: a wave's binds, status
        marks and reflector write-backs cost one lock acquisition and one
        contiguous resourceVersion range instead of N get+update round
        trips (each a lock acquisition plus a conflict-retry risk against
        concurrent writers).

        mutations: iterable of (name, namespace, mutate).  Each mutate
        callback receives a copy-on-write view of the CURRENT object (top
        level and the metadata/spec/status dicts are fresh; anything
        deeper is SHARED with the stored object and must be replaced, not
        mutated in place — the same contract as the engine's
        _update_pod).  A mutate returning False skips the write (no
        resourceVersion bump, no event); objects missing from the store
        are skipped, matching the per-pod path's NotFound no-op.  Per
        object the semantics are update(owned=True): rv stamp, uid/kind
        preservation, pod nodeName write-once validation (a validation
        failure raises mid-batch; earlier writes stand, exactly as the
        sequential loop would have left them).  Watch events fire in
        mutation order under the same lock hold, so subscribers observe
        the batch as one contiguous rv run.  Returns #objects written."""
        from ..utils.tracing import TRACER

        if resource not in self.resources:
            raise NotFound(f"unknown resource {resource}")
        _, namespaced = self.resources[resource]
        written = 0
        try:
            with self._lock:
                for name, namespace, mutate in mutations:
                    key = (f"{namespace or 'default'}/{name}"
                           if namespaced else name)
                    cur = self._objects[resource].get(key)
                    if cur is None:
                        continue
                    # dict(cur) walks dict storage directly (bypassing
                    # LazyManifest overrides) AND the bank columns are
                    # about to move: fill first
                    LazyManifest.ensure(cur)
                    obj = dict(cur)
                    for part in ("metadata", "spec", "status"):
                        if part in obj:
                            obj[part] = dict(obj[part])
                    if mutate(obj) is False:
                        continue
                    if resource == "pods":
                        self._validate_pod_update(key, cur, obj)
                    meta = obj.setdefault("metadata", {})
                    rv = self._next_rv()
                    meta["uid"] = cur["metadata"]["uid"]
                    meta["resourceVersion"] = str(rv)
                    meta.setdefault("creationTimestamp",
                                    cur["metadata"].get("creationTimestamp"))
                    self._stamp_kind(resource, obj)
                    self._objects[resource][key] = obj
                    self._columnar_sync(resource, "update", key, obj)
                    self._notify(resource, MODIFIED, obj, rv)
                    written += 1
                if written:
                    self._bump(resource)
        finally:
            if written:
                TRACER.count("store_batch_writes_total", written)
                TRACER.count("store_batches_total")
        return written

    # ----------------------------------------------------------- watch

    def watch(self, resource: str, since_rv: int = 0) -> queue.Queue:
        """Queue of (rv, event_type, object); buffered events newer than
        since_rv are replayed first.  Call unwatch() when done."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            if resource not in self.resources:
                raise NotFound(f"unknown resource {resource}")
            for ev in self._events[resource]:
                if ev[0] > since_rv:
                    q.put(ev)
            self._watchers[resource].append(q)
        return q

    def list_and_watch(self, resource: str) -> tuple[list[dict], int, queue.Queue]:
        """Atomic list + watch registration: -> (items, rv, queue) where
        the queue carries exactly the events AFTER rv — the informer
        ListAndWatch contract without the ring-buffer race a separate
        list() then watch(since_rv=rv) pair has under heavy concurrent
        write traffic.  Items are the STORED objects (no deep copies,
        the list_shared contract: callers must not mutate them); call
        unwatch() when done with the queue."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            if resource not in self.resources:
                raise NotFound(f"unknown resource {resource}")
            items = [obj for _, obj in sorted(self._objects[resource].items())]
            self._watchers[resource].append(q)
            return items, self._last_rv, q

    def unwatch(self, resource: str, q: queue.Queue) -> None:
        with self._lock:
            try:
                self._watchers[resource].remove(q)
            except ValueError:
                pass

    # ----------------------------------------------------------- etcd analogue

    def dump(self) -> dict:
        """Full keyspace snapshot (the etcd-prefix dump reset takes at boot,
        reference: reset/reset.go:32-55)."""
        if self._read_hooks:
            # snapshot fidelity: deferred annotations must be on the
            # objects the dump captures
            self.materialize_reads()
        with self._lock:
            # shallow per-resource snapshot under the lock pins the exact
            # keyspace state; the heavy deep copy happens outside it
            # (stored objects are never mutated in place)
            snap = {r: dict(objs) for r, objs in self._objects.items()}
        return copy.deepcopy(snap)

    def restore(self, kvs: dict) -> None:
        """Delete-prefix + re-put (reference: reset/reset.go:57-78).  Watch
        subscribers receive DELETED/ADDED events for the transition."""
        # copy the incoming keyspace BEFORE taking the lock: the caller's
        # dicts must not be shared with stored state, but the O(keyspace)
        # deep copy has no business inside the write lock hold
        copies = {resource: {key: copy.deepcopy(obj)
                             for key, obj in objs.items()}
                  for resource, objs in kvs.items()}
        if self._read_hooks:
            # the replaced keyspace invalidates every deferred record
            # (new incarnations, new uids): drop them all
            self._discard_hooks(None)
        with self._lock:
            for resource in list(self.resources):
                for key in list(self._objects[resource]):
                    cur = self._objects[resource].pop(key)
                    self._notify(resource, DELETED, cur, self._next_rv())
                self._bump(resource)
            # fresh banks for the restored keyspace; popped lazy rows
            # keep their old bank alive through their own reference
            if self._columnar:
                for resource, factory in _COLUMNAR_BANKS.items():
                    bank = factory()
                    bank.uid_factory = _new_uid
                    self._banks[resource] = bank
            for resource, objs in copies.items():
                if resource not in self.resources and objs:
                    # a dump from a store with registered extras: infer
                    # the registration from the objects themselves
                    first = next(iter(objs.values()))
                    self.register_resource(
                        resource, first.get("kind") or resource.capitalize(),
                        namespaced="/" in next(iter(objs)),
                        api_version=first.get("apiVersion") or "v1")
                for key, obj in objs.items():
                    self._objects[resource][key] = obj
                    self._columnar_sync(resource, "create", key, obj)
                    self._notify(resource, ADDED, obj, self._next_rv())
                self._bump(resource)


def list_shared(store, resource: str) -> list[dict]:
    """Read-only listing without per-object deep copies — the engine's
    informer-cache fast path (callers MUST NOT mutate the returned
    manifests).  Stores without a `copy_objects` parameter (e.g. the
    remote HTTP cluster client) fall back to the plain listing.  The
    capability is probed ONCE per store by signature inspection and
    cached on the store object, so a TypeError raised inside a
    conforming store's list body propagates instead of being
    misread as "no fast path"."""
    fast = getattr(store, "_shared_list_ok", None)
    if fast is None:
        import inspect

        try:
            fast = "copy_objects" in inspect.signature(store.list).parameters
        except (TypeError, ValueError):
            fast = False
        try:
            store._shared_list_ok = fast
        except AttributeError:
            pass  # __slots__ store: re-probe next time
    if fast:
        return store.list(resource, copy_objects=False)[0]
    return store.list(resource)[0]
