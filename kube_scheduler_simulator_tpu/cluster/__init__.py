from .store import ObjectStore, ApiError, RESOURCES, Conflict, NotFound, AlreadyExists  # noqa: F401
