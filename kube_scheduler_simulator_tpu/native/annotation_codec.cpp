// Native annotation codec — the host-side hot path of the reflector.
//
// The reference serializes scheduling results to Pod annotations in Go
// (simulator/scheduler/plugin/resultstore/store.go:133-198); at 10k pods x
// 5k nodes the filter/score/finalscore JSON blobs dominate host time in
// this framework's write-back path, so they are encoded here in C++ and
// exposed over a C ABI consumed via ctypes (no pybind11 in this image).
//
// Encoding contract (byte-identical to Go encoding/json):
//   * compact (no spaces), map keys sorted lexicographically (Go sorts
//     map keys when marshaling);
//   * strings escaped per encoding/json: ", \\, control chars, and the
//     HTML-safe set < > & as < > &;
//   * filter map reproduces the framework's stop-at-first-fail truncation:
//     plugins in execution order until the first failure, keys sorted in
//     the output object.
//
// Message resolution is table-driven: per filter plugin a LUT indexed by
// (code-1), either shared across nodes or per-node (taint messages embed
// the node's taint key/value).  Python builds the LUTs once per compiled
// workload.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <charconv>
#include <cstring>
#include <cstdlib>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <algorithm>
#include <vector>

namespace {

// one string VALUE, quotes included — Python json.dumps(ensure_ascii=
// False) escapes (incl. the \b/\f shortcuts) plus Go's HTML escaping of
// < > & , matching store/annotations.py marshal() byte-for-byte
// needs_escape[c]: byte c cannot be copied verbatim inside a JSON string
struct EscTable {
    bool t[256] = {};
    EscTable() {
        for (int c = 0; c < 0x20; ++c) t[c] = true;
        t[(unsigned char)'"'] = t[(unsigned char)'\\'] = true;
        t[(unsigned char)'<'] = t[(unsigned char)'>'] = t[(unsigned char)'&'] = true;
    }
};
const EscTable kEsc;

void append_escaped_n(std::string& out, const char* s, size_t len) {
    out.push_back('"');
    size_t i = 0;
    while (i < len) {
        // bulk-copy the run up to the next byte needing escape (values
        // are whole JSON blobs, so runs average ~a dozen bytes between
        // quotes — still ~2x over the per-char switch)
        size_t run = i;
        while (run < len && !kEsc.t[(unsigned char)s[run]]) ++run;
        if (run > i) {
            out.append(s + i, run - i);
            i = run;
        }
        if (i >= len) break;
        unsigned char c = (unsigned char)s[i++];
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\b': out += "\\b"; break;
            case '\f': out += "\\f"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            case '<': out += "\\u003c"; break;
            case '>': out += "\\u003e"; break;
            case '&': out += "\\u0026"; break;
            default: {
                char buf[8];
                snprintf(buf, sizeof buf, "\\u%04x", c);
                out += buf;
            }
        }
    }
    out.push_back('"');
}

void append_escaped(std::string& out, const char* s) {
    append_escaped_n(out, s, std::strlen(s));
}

char* dup_string(const std::string& s) {
    char* out = (char*)std::malloc(s.size() + 1);
    std::memcpy(out, s.c_str(), s.size() + 1);
    return out;
}

// quoted integer without snprintf (the per-value %lld dominated the
// score-blob encode time at cluster scale: ~3 ms -> ~0.3 ms per blob)
void append_quoted_int(std::string& out, long long v) {
    char tmp[24];
    auto r = std::to_chars(tmp, tmp + sizeof tmp, v);
    out.push_back('"');
    out.append(tmp, (size_t)(r.ptr - tmp));
    out.push_back('"');
}

}  // namespace

extern "C" {

void codec_free(char* p) { std::free(p); }

// {"key":"value",...} from pre-sorted keys — the result-history record
// encoder (values are whole annotation blobs, so the escape pass over
// hundreds of KiB is the hot part; byte-identical to marshal(dict))
char* encode_string_map(const char* const* keys,
                        const char* const* vals,
                        const long long* val_lens,
                        long long n) {
    size_t cap = 2;
    for (long long i = 0; i < n; ++i) cap += (size_t)val_lens[i] + 48;
    std::string out;
    out.reserve(cap);
    out.push_back('{');
    for (long long i = 0; i < n; ++i) {
        if (i) out.push_back(',');
        append_escaped(out, keys[i]);
        out.push_back(':');
        append_escaped_n(out, vals[i], (size_t)val_lens[i]);
    }
    out.push_back('}');
    return dup_string(out);
}

// encode_string_map with the output length returned (out_len) so the
// caller can build the str in one sized copy instead of a NUL-scan +
// bytes round-trip — the history-record encode runs once per pod per
// wave and its values are ~250KB of blobs, so the extra pass is real.
// ascii_only is set when every emitted byte is ASCII (escaping only
// ever emits ASCII for ASCII input; a non-ASCII input byte is copied
// through verbatim), letting the caller skip UTF-8 validation.
char* encode_string_map_sized(const char* const* keys,
                              const char* const* vals,
                              const long long* val_lens,
                              long long n,
                              long long* out_len,
                              int32_t* ascii_only) {
    size_t cap = 2;
    for (long long i = 0; i < n; ++i) cap += (size_t)val_lens[i] + 48;
    std::string out;
    out.reserve(cap);
    out.push_back('{');
    for (long long i = 0; i < n; ++i) {
        if (i) out.push_back(',');
        append_escaped(out, keys[i]);
        out.push_back(':');
        append_escaped_n(out, vals[i], (size_t)val_lens[i]);
    }
    out.push_back('}');
    if (out_len) *out_len = (long long)out.size();
    if (ascii_only) {
        int32_t ascii = 1;
        for (unsigned char c : out) if (c >= 0x80) { ascii = 0; break; }
        *ascii_only = ascii;
    }
    return dup_string(out);
}

// filter-result: {"node":{"Plugin":"passed"|msg,...},...}
//
// codes:        [F*N] int32, 0 == pass (plugin-skip already zeroed)
// active:       [F] uint8 — plugins whose Filter ran for this pod
// sorted_nodes: [N] int32 — node indices in lexicographic name order
// sorted_plugins_by_name: [F] int32 — plugin indices sorted by name
// lut_flat/lut_off: message LUTs; for plugin f the LUT spans
//     lut_flat[lut_off[f] .. lut_off[f+1]) ; node-dependent plugins
//     (per_node[f] != 0) use stride = (lut_off[f+1]-lut_off[f])/N per node.
char* encode_filter_result(
    int32_t n, int32_t f,
    const int32_t* codes,
    const uint8_t* active,
    const char* const* node_names,
    const char* const* plugin_names,
    const int32_t* sorted_nodes,
    const int32_t* sorted_plugins_by_name,
    const char* const* lut_flat,
    const int32_t* lut_off,
    const uint8_t* per_node) {
    std::string out;
    out.reserve((size_t)n * 64);
    out.push_back('{');
    bool any_active = false;
    for (int32_t pf = 0; pf < f; ++pf) any_active |= (bool)active[pf];
    bool first_node = true;
    for (int32_t si = 0; si < n && any_active; ++si) {
        int32_t j = sorted_nodes[si];
        // index (in execution order) of the first failing active plugin
        int32_t fail_at = f;
        for (int32_t pf = 0; pf < f; ++pf) {
            if (active[pf] && codes[(size_t)pf * n + j] != 0) { fail_at = pf; break; }
        }
        if (!first_node) out.push_back(',');
        first_node = false;
        append_escaped(out, node_names[j]);
        out.push_back(':');
        out.push_back('{');
        // entries: active plugins with index <= fail_at, sorted by name
        bool first_plugin = true;
        for (int32_t k = 0; k < f; ++k) {
            int32_t pf = sorted_plugins_by_name[k];
            if (!active[pf] || pf > fail_at) continue;
            const char* msg;
            int32_t code = codes[(size_t)pf * n + j];
            if (code == 0) {
                msg = "passed";
            } else {
                int32_t span = lut_off[pf + 1] - lut_off[pf];
                int32_t base = lut_off[pf];
                if (per_node[pf]) {
                    int32_t stride = span / n;
                    msg = lut_flat[base + (size_t)j * stride + (code - 1)];
                } else {
                    msg = lut_flat[base + (code - 1)];
                }
            }
            if (!first_plugin) out.push_back(',');
            first_plugin = false;
            append_escaped(out, plugin_names[pf]);
            out.push_back(':');
            append_escaped(out, msg);
        }
        out.push_back('}');
    }
    out.push_back('}');
    return dup_string(out);
}

// score-result / finalscore-result: {"node":{"Plugin":"<int>",...},...}
// over feasible nodes only; plugins with sskip are omitted.  Values are
// int64 (upstream node scores are int64; custom plugins can exceed int32).
char* encode_score_result(
    int32_t n, int32_t s,
    const int64_t* values,           // [S*N]
    const uint8_t* sskip,            // [S]
    const uint8_t* feasible,         // [N]
    const char* const* node_names,
    const char* const* score_names,
    const int32_t* sorted_nodes,
    const int32_t* sorted_scores_by_name) {
    std::string out;
    out.reserve((size_t)n * 48);
    out.push_back('{');
    bool first_node = true;
    for (int32_t si = 0; si < n; ++si) {
        int32_t j = sorted_nodes[si];
        if (!feasible[j]) continue;
        bool any = false;
        for (int32_t q = 0; q < s; ++q) if (!sskip[q]) { any = true; break; }
        if (!any) continue;
        if (!first_node) out.push_back(',');
        first_node = false;
        append_escaped(out, node_names[j]);
        out.push_back(':');
        out.push_back('{');
        bool first_sc = true;
        for (int32_t k = 0; k < s; ++k) {
            int32_t q = sorted_scores_by_name[k];
            if (sskip[q]) continue;
            if (!first_sc) out.push_back(',');
            first_sc = false;
            append_escaped(out, score_names[q]);
            out.push_back(':');
            append_quoted_int(out, (long long)values[(size_t)q * n + j]);
        }
        out.push_back('}');
    }
    out.push_back('}');
    return dup_string(out);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Context API — the per-workload fast path.
//
// Everything that is constant across pods (escaped node-name keys, escaped
// plugin-name keys, escaped failure messages) is escaped ONCE at context
// build; per-pod encoding is then fragment memcpy + integer formatting.
// At 5k nodes this moves the encoder from ~300 MB/s (per-char escape
// switch) to multi-GB/s fragment assembly — the decode-inclusive
// end-to-end number rides on this loop.

namespace {

struct Ctx {
    uint64_t uid = 0;                     // for thread-local cache keying
    int32_t n = 0, f = 0, s = 0;
    std::vector<int32_t> sorted_nodes;    // si -> node index j (name order)
    std::vector<int32_t> sorted_filters;  // k -> filter exec index (name order)
    std::vector<int32_t> sorted_scores;   // k -> scorer index (name order)
    std::vector<std::string> node_key;    // per node j: `"name":` escaped
    std::vector<std::string> filter_key;  // per filter pf: `"Name":`
    std::vector<std::string> score_key;   // per scorer q: `"Name":`
    std::vector<std::string> lut;         // escaped messages, quotes included
    std::vector<int32_t> lut_off;
    std::vector<uint8_t> per_node;
    size_t max_msg = 0;                   // longest LUT message (reserve hint)
    size_t sum_node_key = 0;              // Σ node_key sizes (cap computation)
    // score finalization (the host mirror of framework/hostnorm.py):
    // kind 0 = passthrough, 1 = default, 2 = default-reverse,
    // 3 = PodTopologySpread, 4 = InterPodAffinity
    std::vector<int32_t> score_kind;
    std::vector<int64_t> score_weight;
    int64_t tsp_big = 0;
    // 1 when every fragment this ctx can emit is pure ASCII (append_escaped
    // passes bytes >= 0x80 through verbatim, so non-ASCII names/messages
    // clear it); lets the Python side build result strs with a plain
    // memcpy instead of a UTF-8-validating decode
    int32_t all_ascii = 1;
};

bool str_is_ascii(const std::string& s) {
    for (unsigned char c : s) if (c >= 0x80) return false;
    return true;
}

// raw output buffer: one malloc sized from an upper bound, pointer-bump
// writes (std::string's per-append capacity checks and the final
// dup_string copy both showed up at 5k-node scale)
inline void put(char*& w, const std::string& s) {
    std::memcpy(w, s.data(), s.size());
    w += s.size();
}
inline void put(char*& w, const char* s, size_t len) {
    std::memcpy(w, s, len);
    w += len;
}

std::string escaped_key(const char* name) {
    std::string out;
    append_escaped(out, name);
    out.push_back(':');
    return out;
}

}  // namespace

namespace {

// shared filter-blob machinery for ctx_encode_filter / ctx_decode_pod —
// the two entry points differ only in WHERE the per-node first-fail
// (fail_at, code) comes from (unpacked [F,N] codes vs the packed word);
// fragment construction and the emit loop are one implementation so the
// byte contract cannot diverge between them.
struct FilterFrags {
    struct Frag { std::string head, tail; bool used = false; };
    std::string all_pass;
    std::vector<Frag> frag;
    size_t max_frag = 0;
    bool any_active = false;
};

// Everything about the filter blob that depends only on (workload,
// active set) — i.e. NOT on the per-pod codes: the per-fail-plugin
// fragments, and `cat`, the full concatenation over name-sorted nodes of
// "," + node_key + all_pass with per-node offsets.  Workloads run the
// same active set for nearly every pod, and most nodes pass every
// filter, so a pod's blob is mostly maximal RUNS of consecutive all-pass
// nodes — each run emits as ONE memcpy out of `cat` (measured: the
// per-node emit loop was the largest decode slice at 5k nodes, ~0.36
// ms/pod; runs cut it to near-memcpy).  Cached thread-local, one entry
// (active sets change between pods only on PreFilter-skip boundaries).
struct FilterCache {
    uint64_t uid = ~0ull;
    uint64_t mask = 0;
    bool valid = false;
    FilterFrags ff;
    std::string cat;
    std::vector<uint32_t> off;  // [n+1] into cat
    // pre-rendered head+msg+tail per (fail plugin, code) for plugins with
    // a SHARED (not per-node) message LUT: a failing node then emits as
    // key + ONE suffix memcpy instead of three puts
    std::vector<std::string> suffix;      // indexed lut_off[pf] + code-1
    std::vector<uint8_t> suffix_ok;       // same indexing; 0 = per-node LUT
};

void build_filter_frags(const Ctx& ctx, const uint8_t* active, FilterFrags& ff) {
    const int32_t f = ctx.f;
    // reset alongside all_pass/frag: FilterFrags lives inside reused
    // FilterCache slots (round-robin eviction, and the f>64 thread_local),
    // so a stale true would make an empty-active pod emit per-node {}
    // objects instead of "{}" — and cache the wrong blob
    ff.any_active = false;
    ff.all_pass = "{";
    bool first = true;
    for (int32_t k = 0; k < f; ++k) {
        int32_t pf = ctx.sorted_filters[k];
        if (!active[pf]) continue;
        ff.any_active = true;
        if (!first) ff.all_pass.push_back(',');
        first = false;
        ff.all_pass += ctx.filter_key[pf];
        ff.all_pass += "\"passed\"";
    }
    ff.all_pass.push_back('}');
    ff.frag.assign(f, {});
    for (int32_t pf_fail = 0; pf_fail < f; ++pf_fail) {
        if (!active[pf_fail]) continue;
        FilterFrags::Frag& fr = ff.frag[pf_fail];
        fr.used = true;
        fr.head = "{";
        bool frst = true, before = true;
        for (int32_t k = 0; k < f; ++k) {
            int32_t pf = ctx.sorted_filters[k];
            if (!active[pf] || pf > pf_fail) continue;
            std::string& dst = before ? fr.head : fr.tail;
            if (pf == pf_fail) {
                if (!frst) fr.head.push_back(',');
                fr.head += ctx.filter_key[pf];
                before = false;
            } else {
                if (!frst) dst.push_back(',');
                dst += ctx.filter_key[pf];
                dst += "\"passed\"";
            }
            frst = false;
        }
        fr.tail.push_back('}');
    }
    ff.max_frag = ff.all_pass.size();
    for (const FilterFrags::Frag& fr : ff.frag) if (fr.used)
        ff.max_frag = std::max(ff.max_frag,
                               fr.head.size() + ctx.max_msg + fr.tail.size());
}

// thread_local: ctx_decode_pod runs from a decode thread pool; each
// thread keeps its own cache so no locking is needed.  Keyed by
// (ctx uid, active bitmask); several entries live at once because pods
// ALTERNATE between a handful of active sets (PreFilter-skip patterns —
// measured 4 distinct masks at config 4 with the mask changing between
// ~76% of consecutive pods, so a single-entry cache would rebuild its
// ~1 MB cat nearly every pod).  f > 64 filters disables caching
// (rebuild per pod — no real lineup is that large).
const FilterCache& filter_cache_for(const Ctx& ctx, const uint8_t* active) {
    thread_local std::vector<FilterCache> caches;
    thread_local size_t victim = 0;
    FilterCache* cache = nullptr;
    uint64_t mask = 0;
    bool cacheable = ctx.f <= 64;
    if (cacheable) {
        for (int32_t pf = 0; pf < ctx.f; ++pf)
            if (active[pf]) mask |= 1ull << pf;
        for (FilterCache& c : caches)
            if (c.valid && c.uid == ctx.uid && c.mask == mask) return c;
        if (caches.size() < 8) {
            caches.emplace_back();
            cache = &caches.back();
        } else {
            cache = &caches[victim];       // round-robin eviction
            victim = (victim + 1) % caches.size();
        }
    } else {
        thread_local FilterCache uncached;
        cache = &uncached;
    }
    cache->valid = cacheable;
    cache->uid = ctx.uid;
    cache->mask = mask;
    build_filter_frags(ctx, active, cache->ff);
    if (!cacheable) {
        // the run/suffix paths check fc.valid and can never read these —
        // don't pay the O(n) concatenation per pod on the uncached path
        cache->cat.clear();
        cache->off.clear();
        cache->suffix.clear();
        cache->suffix_ok.clear();
        return *cache;
    }
    const int32_t n = ctx.n;
    cache->cat.clear();
    cache->cat.reserve(ctx.sum_node_key
                       + (size_t)n * (1 + cache->ff.all_pass.size()));
    cache->off.assign((size_t)n + 1, 0);
    for (int32_t si = 0; si < n; ++si) {
        int32_t j = ctx.sorted_nodes[si];
        cache->cat.push_back(',');
        cache->cat += ctx.node_key[j];
        cache->cat += cache->ff.all_pass;
        cache->off[(size_t)si + 1] = (uint32_t)cache->cat.size();
    }
    int32_t total = ctx.lut_off.empty() ? 0 : ctx.lut_off.back();
    cache->suffix.assign(total, {});
    cache->suffix_ok.assign(total, 0);
    for (int32_t pf = 0; pf < ctx.f; ++pf) {
        if (!active[pf] || ctx.per_node[pf]) continue;
        const FilterFrags::Frag& fr = cache->ff.frag[pf];
        for (int32_t c = ctx.lut_off[pf]; c < ctx.lut_off[pf + 1]; ++c) {
            cache->suffix[c] = fr.head + ctx.lut[c] + fr.tail;
            cache->suffix_ok[c] = 1;
        }
    }
    return *cache;
}

// fail_buf[j]: first-fail exec idx (f = all active passed); code_buf[j]:
// the failing plugin's code (only read when fail_buf[j] < f).
// n_fail picks the emit strategy: when failures are rare, maximal runs
// of consecutive all-pass nodes memcpy straight out of the cached `cat`
// (one big copy per run); when failures are dense the runs are short
// (measured mean 2 at config 4's ~55% fail rate) and walking the ~1 MB
// cat in scattered pieces costs more cache traffic than rendering from
// the small L1-resident fragments — so the per-node path is kept, with
// the pre-rendered (plugin, code) suffix turning a failing node into
// two memcpys.
char* emit_filter_blob(const Ctx& ctx, const FilterCache& fc,
                       const int32_t* fail_buf, const int32_t* code_buf,
                       int32_t n_fail, int64_t* out_len) {
    const FilterFrags& ff = fc.ff;
    const int32_t n = ctx.n, f = ctx.f;
    size_t cap = 3 + (ff.any_active
        ? ctx.sum_node_key + (size_t)n * (1 + ff.max_frag) : 0);
    char* buf = (char*)std::malloc(cap);
    char* w = buf;
    *w++ = '{';
    bool first_node = true;
    // mean all-pass run length >= ~128 nodes before the cat walk pays
    const bool use_runs = fc.valid && n_fail * 128 < n;
    int32_t si = 0;
    while (si < n && ff.any_active) {
        int32_t j = ctx.sorted_nodes[si];
        int32_t fail_at = fail_buf[j];
        if (fail_at == f && use_runs) {
            // maximal run of consecutive all-pass nodes -> one memcpy of
            // the cached ",node":{...passed...}" bytes (skip the leading
            // comma at blob start)
            int32_t run_end = si + 1;
            while (run_end < n && fail_buf[ctx.sorted_nodes[run_end]] == f)
                ++run_end;
            const char* src = fc.cat.data() + fc.off[si];
            size_t len = fc.off[run_end] - fc.off[si];
            if (first_node) { ++src; --len; first_node = false; }
            put(w, src, len);
            si = run_end;
            continue;
        }
        if (!first_node) *w++ = ',';
        first_node = false;
        put(w, ctx.node_key[j]);
        if (fail_at == f) {
            put(w, ff.all_pass);
            ++si;
            continue;
        }
        int32_t base = ctx.lut_off[fail_at];
        int32_t code = code_buf[j];
        if (fc.valid && fc.suffix_ok[base + (code - 1)]) {
            put(w, fc.suffix[base + (code - 1)]);
            ++si;
            continue;
        }
        const FilterFrags::Frag& fr = ff.frag[fail_at];
        put(w, fr.head);
        int32_t span = ctx.lut_off[fail_at + 1] - ctx.lut_off[fail_at];
        if (ctx.per_node[fail_at]) {
            int32_t stride = span / n;
            put(w, ctx.lut[base + (size_t)j * stride + (code - 1)]);
        } else {
            put(w, ctx.lut[base + (code - 1)]);
        }
        put(w, fr.tail);
        ++si;
    }
    *w++ = '}';
    *w = 0;
    *out_len = (int64_t)(w - buf);
    return buf;
}

}  // namespace

extern "C" {

void* codec_ctx_new(
    int32_t n, int32_t f, int32_t s,
    const char* const* node_names,
    const char* const* filter_names,
    const char* const* score_names,
    const int32_t* sorted_nodes,
    const int32_t* sorted_filters,
    const int32_t* sorted_scores,
    const char* const* lut_flat,
    const int32_t* lut_off,
    const uint8_t* per_node,
    const int32_t* score_kind,
    const int64_t* score_weight,
    int64_t tsp_big) {
    Ctx* ctx = new Ctx();
    static std::atomic<uint64_t> next_uid{1};
    ctx->uid = next_uid.fetch_add(1);
    ctx->n = n; ctx->f = f; ctx->s = s;
    ctx->sorted_nodes.assign(sorted_nodes, sorted_nodes + n);
    ctx->sorted_filters.assign(sorted_filters, sorted_filters + f);
    ctx->sorted_scores.assign(sorted_scores, sorted_scores + s);
    ctx->node_key.reserve(n);
    for (int32_t j = 0; j < n; ++j) {
        ctx->node_key.push_back(escaped_key(node_names[j]));
        ctx->sum_node_key += ctx->node_key.back().size();
    }
    ctx->filter_key.reserve(f);
    for (int32_t pf = 0; pf < f; ++pf) ctx->filter_key.push_back(escaped_key(filter_names[pf]));
    ctx->score_key.reserve(s);
    for (int32_t q = 0; q < s; ++q) ctx->score_key.push_back(escaped_key(score_names[q]));
    ctx->lut_off.assign(lut_off, lut_off + f + 1);
    ctx->per_node.assign(per_node, per_node + f);
    int32_t total = ctx->lut_off.empty() ? 0 : ctx->lut_off.back();
    ctx->lut.reserve(total);
    for (int32_t i = 0; i < total; ++i) {
        std::string m;
        append_escaped(m, lut_flat[i]);
        ctx->max_msg = std::max(ctx->max_msg, m.size());
        ctx->lut.push_back(std::move(m));
    }
    ctx->score_kind.assign(score_kind, score_kind + s);
    ctx->score_weight.assign(score_weight, score_weight + s);
    ctx->tsp_big = tsp_big;
    for (const auto& v : {&ctx->node_key, &ctx->filter_key,
                          &ctx->score_key, &ctx->lut})
        for (const std::string& str : *v)
            if (!str_is_ascii(str)) { ctx->all_ascii = 0; break; }
    return ctx;
}

int32_t ctx_all_ascii(void* p) { return ((const Ctx*)p)->all_ascii; }

void codec_ctx_free(void* p) { delete (Ctx*)p; }

char* ctx_encode_filter(void* p, const int32_t* codes, const uint8_t* active,
                        int64_t* out_len) {
    const Ctx& ctx = *(const Ctx*)p;
    const int32_t n = ctx.n, f = ctx.f;
    thread_local std::vector<int32_t> fail_buf;
    thread_local std::vector<int32_t> code_buf;
    fail_buf.resize(n);
    code_buf.resize(n);
    int32_t n_fail = 0;
    for (int32_t j = 0; j < n; ++j) {
        int32_t fail_at = f, code = 0;
        for (int32_t pf = 0; pf < f; ++pf) {
            if (active[pf] && codes[(size_t)pf * n + j] != 0) {
                fail_at = pf; code = codes[(size_t)pf * n + j]; break;
            }
        }
        fail_buf[j] = fail_at;
        code_buf[j] = code;
        n_fail += (fail_at != f);
    }
    return emit_filter_blob(ctx, filter_cache_for(ctx, active),
                            fail_buf.data(), code_buf.data(), n_fail,
                            out_len);
}

// Fused per-pod decode from the COMPACT replay layout: reads the packed
// first-fail word and the narrow typed score columns directly, computes
// finalscore (the framework/hostnorm.py math, bit-exact incl. numpy's
// floor division) in place, and emits the three heavy blobs in one call.
// This removes the [C,F,N] code unpack and the [C,S,N] int64 raw/final
// materialization from the decode hot path entirely.
//
//   packed:     [N] little-endian words, elem size pack_elem (1/2/4/8);
//               word = code | (first_fail_idx+1) << code_bits; 0 = pass
//   score_cols: [S] pointers to this pod's raw column, elem size
//               score_elem[q] (1/2/4/8), signed
//   ignored:    [N] PodTopologySpread score-ignore mask (NULL = none)
//   want_scores: feasible_count > 1 (upstream skips scoring otherwise)
//   out_blobs/out_lens: filter-result, score-result, finalscore-result;
//               score slots are NULL when want_scores is 0
namespace {

inline int64_t floordiv(int64_t a, int64_t b) {
    int64_t q = a / b;
    if ((a % b != 0) && ((a < 0) != (b < 0))) --q;
    return q;
}

inline uint64_t read_packed(const void* packed, int32_t elem, int32_t j) {
    switch (elem) {
        case 1: return ((const uint8_t*)packed)[j];
        case 2: return ((const uint16_t*)packed)[j];
        case 4: return (uint64_t)((const int32_t*)packed)[j];
        default: return (uint64_t)((const int64_t*)packed)[j];
    }
}

inline int64_t read_score(const void* col, int32_t elem, int32_t j) {
    switch (elem) {
        case 1: return ((const int8_t*)col)[j];
        case 2: return ((const int16_t*)col)[j];
        case 4: return ((const int32_t*)col)[j];
        default: return ((const int64_t*)col)[j];
    }
}

// decode_one: the per-pod body shared by ctx_decode_pod (one C call per
// pod, the legacy fused path) and ctx_decode_chunk (one C call per replay
// chunk, pods iterated by the worker pool).  Runs on any thread; all
// scratch state is thread_local.
int32_t decode_one(
    const Ctx& ctx,
    const void* packed, int32_t pack_elem, int32_t code_bits,
    const uint8_t* active,
    const uint8_t* sskip,
    const void* const* score_cols, const int32_t* score_elem,
    const uint8_t* ignored,
    int32_t want_scores,
    char** out_blobs, int64_t* out_lens) {
    const int32_t n = ctx.n, f = ctx.f, s = ctx.s;
    const uint64_t code_mask = (code_bits >= 64) ? ~0ull : ((1ull << code_bits) - 1);

    thread_local std::vector<uint8_t> feas_buf;
    thread_local std::vector<int32_t> fail_buf;   // first-fail exec idx, f = pass
    thread_local std::vector<int32_t> code_buf;
    feas_buf.resize(n);
    fail_buf.resize(n);
    code_buf.resize(n);

    int32_t n_fail = 0;
    for (int32_t j = 0; j < n; ++j) {
        uint64_t w = read_packed(packed, pack_elem, j);
        int32_t ffp = (int32_t)(w >> code_bits);
        int32_t code = (int32_t)(w & code_mask);
        feas_buf[j] = (ffp == 0);  // replay.py recon: feasible = ffp == 0
        if (ffp > 0 && ffp <= f && code != 0 && active[ffp - 1]) {
            fail_buf[j] = ffp - 1;
            code_buf[j] = code;
            ++n_fail;
        } else {
            fail_buf[j] = f;  // all active plugins passed (or fail not active)
            code_buf[j] = 0;
        }
    }

    out_blobs[0] = emit_filter_blob(ctx, filter_cache_for(ctx, active),
                                    fail_buf.data(), code_buf.data(), n_fail,
                                    &out_lens[0]);
    out_blobs[1] = out_blobs[2] = nullptr;
    out_lens[1] = out_lens[2] = 0;
    if (!want_scores) return 0;

    // ---- distinct-tuple pass (hostnorm mirrors) ------------------------
    //
    // Workloads cluster: at the 5k-node shape only ~0.5% of feasible
    // nodes carry a DISTINCT (raw values, ignored) tuple, and both the
    // reductions (max/min ignore multiplicity) and the normalization are
    // pure functions of that tuple + per-pod state.  So: hash every
    // feasible node's tuple ONCE, compute reductions over the distinct
    // entries, render each distinct score/finalscore row suffix once,
    // and emit = node key + two memcpys per node.  Byte-identical to the
    // per-node math (the 0 floors below replicate the per-node loops'
    // accumulator init values); measured ~3x on the score/final side.
    std::vector<std::string> prefix;
    std::vector<int32_t> act;
    prefix.reserve(s);
    act.reserve(s);
    size_t row_fixed = 3;
    for (int32_t k = 0; k < s; ++k) {
        int32_t q = ctx.sorted_scores[k];
        if (sskip[q]) continue;
        std::string pre(act.empty() ? "{" : ",");
        pre += ctx.score_key[q];
        pre.push_back('"');
        row_fixed += pre.size() + 21;
        prefix.push_back(std::move(pre));
        act.push_back(q);
    }

    size_t cap = 3 + (act.empty() ? 0 : ctx.sum_node_key + (size_t)n * (1 + row_fixed));
    char* sbuf = (char*)std::malloc(cap);
    char* fbuf = (char*)std::malloc(cap);
    char* sw = sbuf;
    char* fw = fbuf;
    *sw++ = '{';
    *fw++ = '{';
    bool first_node = true;
    if (!act.empty()) {
        const size_t kvals = act.size();
        struct Entry {
            uint64_t hash; uint32_t val_off;
            uint32_t s_off, s_len, f_off, f_len;
            uint8_t ig;
        };
        thread_local std::vector<Entry> entries;
        thread_local std::vector<uint32_t> table;  // slot -> entry id + 1
        thread_local std::vector<int64_t> val_store;
        thread_local std::vector<int32_t> ent_of;  // node -> entry id (-1 infeasible)
        thread_local std::vector<int64_t> vals;
        thread_local std::string scr_s, scr_f;
        entries.clear();
        val_store.clear();
        scr_s.clear();
        scr_f.clear();
        table.assign(256, 0);  // grows 4x at 1/2 load
        size_t tmask = table.size() - 1;
        ent_of.assign(n, -1);
        vals.resize(kvals);

        // pass 1: dedup every feasible node's tuple
        for (int32_t j = 0; j < n; ++j) {
            if (!feas_buf[j]) continue;
            uint64_t h = 1469598103934665603ull;  // FNV-1a over the tuple
            for (size_t k = 0; k < kvals; ++k) {
                int64_t v = read_score(score_cols[act[k]], score_elem[act[k]], j);
                vals[k] = v;
                h ^= (uint64_t)v;
                h *= 1099511628211ull;
            }
            uint8_t ig = (ignored && ignored[j]) ? 1 : 0;
            h ^= ig;
            h *= 1099511628211ull;

            size_t slot = (size_t)h & tmask;
            int32_t eid = -1;
            for (;;) {
                uint32_t ref = table[slot];
                if (!ref) break;
                const Entry& e = entries[ref - 1];
                if (e.hash == h && e.ig == ig &&
                    std::memcmp(&val_store[e.val_off], vals.data(),
                                kvals * sizeof(int64_t)) == 0) {
                    eid = (int32_t)(ref - 1);
                    break;
                }
                slot = (slot + 1) & tmask;
            }
            if (eid < 0) {
                eid = (int32_t)entries.size();
                Entry e{};
                e.hash = h;
                e.ig = ig;
                e.val_off = (uint32_t)val_store.size();
                val_store.insert(val_store.end(), vals.begin(), vals.end());
                entries.push_back(e);
                table[slot] = (uint32_t)eid + 1;
                if (entries.size() * 2 > table.size()) {  // grow + rehash
                    table.assign(table.size() * 4, 0);
                    tmask = table.size() - 1;
                    for (size_t t2 = 0; t2 < entries.size(); ++t2) {
                        size_t s2 = (size_t)entries[t2].hash & tmask;
                        while (table[s2]) s2 = (s2 + 1) & tmask;
                        table[s2] = (uint32_t)t2 + 1;
                    }
                }
            }
            ent_of[j] = eid;
        }

        // pass 2: reductions over the distinct tuples
        struct Red { int64_t mn, mx; };
        std::vector<Red> red(kvals);
        for (size_t k = 0; k < kvals; ++k) {
            int32_t kind = ctx.score_kind[act[k]];
            Red r{0, 0};
            if (kind == 1 || kind == 2) {
                // default_normalize: max over feasible of raw (0 floor)
                int64_t mx = 0;
                for (const Entry& e : entries) {
                    int64_t v = val_store[e.val_off + k];
                    if (v > mx) mx = v;
                }
                r.mx = mx;
            } else if (kind == 3) {
                int64_t mn = ctx.tsp_big, mx = 0;
                bool any = false;
                for (const Entry& e : entries) {
                    if (e.ig) continue;
                    int64_t v = val_store[e.val_off + k];
                    if (v < mn) mn = v;
                    if (v > mx) mx = v;
                    any = true;
                }
                r.mn = any ? mn : 0;
                r.mx = mx;
            } else if (kind == 4) {
                const int64_t big = (int64_t)1 << 40;
                int64_t mn = big, mx = -big;
                for (const Entry& e : entries) {
                    int64_t v = val_store[e.val_off + k];
                    if (v < mn) mn = v;
                    if (v > mx) mx = v;
                }
                r.mn = mn;
                r.mx = mx;
            }
            red[k] = r;
        }

        // pass 3: render each distinct row suffix once
        char num[24];
        for (Entry& e : entries) {
            e.s_off = (uint32_t)scr_s.size();
            e.f_off = (uint32_t)scr_f.size();
            for (size_t k = 0; k < kvals; ++k) {
                int32_t q = act[k];
                int64_t raw = val_store[e.val_off + k];
                scr_s += prefix[k];
                auto rs = std::to_chars(num, num + 24, (long long)raw);
                scr_s.append(num, rs.ptr - num);
                scr_s.push_back('"');

                int64_t normed;
                const Red& r = red[k];
                switch (ctx.score_kind[q]) {
                    case 1: {  // default_normalize
                        normed = (r.mx == 0)
                            ? raw : floordiv(raw * 100, std::max(r.mx, (int64_t)1));
                        break;
                    }
                    case 2: {  // default reverse (TaintToleration)
                        normed = (r.mx == 0)
                            ? 100 : 100 - floordiv(raw * 100, std::max(r.mx, (int64_t)1));
                        break;
                    }
                    case 3: {  // PodTopologySpread
                        if (e.ig) { normed = 0; break; }
                        normed = (r.mx == 0)
                            ? 100
                            : floordiv(100 * (r.mx + r.mn - raw),
                                       std::max(r.mx, (int64_t)1));
                        break;
                    }
                    case 4: {  // InterPodAffinity (float64 + trunc, like Go)
                        double diff = (double)(r.mx - r.mn);
                        double fv = diff > 0
                            ? 100.0 * ((double)(raw - r.mn) / std::max(diff, 1.0))
                            : 0.0;
                        normed = (int64_t)fv;
                        break;
                    }
                    default: normed = raw;
                }
                scr_f += prefix[k];
                auto rf = std::to_chars(num, num + 24,
                                        (long long)(normed * ctx.score_weight[q]));
                scr_f.append(num, rf.ptr - num);
                scr_f.push_back('"');
            }
            scr_s.push_back('}');
            scr_f.push_back('}');
            e.s_len = (uint32_t)(scr_s.size() - e.s_off);
            e.f_len = (uint32_t)(scr_f.size() - e.f_off);
        }

        // pass 4: emit = node key + two row-suffix memcpys per node
        for (int32_t si = 0; si < n; ++si) {
            int32_t j = ctx.sorted_nodes[si];
            if (ent_of[j] < 0) continue;
            if (!first_node) { *sw++ = ','; *fw++ = ','; }
            first_node = false;
            put(sw, ctx.node_key[j]);
            put(fw, ctx.node_key[j]);
            const Entry& e = entries[ent_of[j]];
            put(sw, scr_s.data() + e.s_off, e.s_len);
            put(fw, scr_f.data() + e.f_off, e.f_len);
        }
    }
    *sw++ = '}'; *sw = 0;
    *fw++ = '}'; *fw = 0;
    out_blobs[1] = sbuf;
    out_lens[1] = (int64_t)(sw - sbuf);
    out_blobs[2] = fbuf;
    out_lens[2] = (int64_t)(fw - fbuf);
    return 0;
}

// ---------------------------------------------------------------------------
// Chunk-granular decode (ctx_decode_chunk): one GIL-released C call per
// replay chunk.  A small persistent worker pool iterates the chunk's pods
// (work-stealing atomic counter); each pod's three blobs land in a
// per-call arena whose addresses/lengths are written into caller arrays,
// so Python builds the result strs with zero per-pod C calls and frees
// everything with ONE chunk_arena_free.  Pool threads persist across
// calls so their thread_local FilterCaches (the ~1 MB per-active-set
// `cat` concatenations) survive from chunk to chunk.

class WorkerPool {
public:
    // fn(worker_idx) on n workers total; the calling thread is worker 0,
    // pool threads are 1..n-1.  Concurrent callers (parallel chunk
    // decodes from several Python threads) don't queue: whoever finds
    // the pool busy just runs inline — the work-stealing loop makes a
    // single worker complete the whole chunk correctly.
    void run(int n, const std::function<void(int)>& fn) {
        if (n <= 1) {  // inline, WITHOUT claiming the pool: a small
            fn(0);     // chunk must not degrade a concurrent big one
            return;
        }
        std::unique_lock<std::mutex> busy(busy_m_, std::try_to_lock);
        if (!busy.owns_lock()) {
            fn(0);
            return;
        }
        {
            std::lock_guard<std::mutex> lk(m_);
            while ((int)threads_.size() < n - 1) {
                int idx = (int)threads_.size() + 1;
                threads_.emplace_back([this, idx] { loop(idx); });
            }
            job_ = &fn;
            target_ = n - 1;
            remaining_ = n - 1;
            ++gen_;
        }
        cv_.notify_all();
        fn(0);
        std::unique_lock<std::mutex> lk(m_);
        done_cv_.wait(lk, [&] { return remaining_ == 0; });
        job_ = nullptr;
    }

private:
    void loop(int idx) {
        uint64_t seen = 0;
        std::unique_lock<std::mutex> lk(m_);
        for (;;) {
            cv_.wait(lk, [&] { return gen_ != seen; });
            seen = gen_;
            if (idx > target_) continue;  // sized out of this round
            const std::function<void(int)>* j = job_;
            lk.unlock();
            (*j)(idx);
            lk.lock();
            if (--remaining_ == 0) done_cv_.notify_one();
        }
    }

    std::mutex busy_m_;  // one chunk in the pool at a time
    std::mutex m_;
    std::condition_variable cv_, done_cv_;
    std::vector<std::thread> threads_;
    const std::function<void(int)>* job_ = nullptr;
    uint64_t gen_ = 0;
    int target_ = 0, remaining_ = 0;
};

// leaked on purpose: joining detached-for-life workers from a static
// destructor would std::terminate at interpreter exit
WorkerPool& decode_pool() {
    static WorkerPool* p = new WorkerPool();
    return *p;
}

struct ChunkArena {
    std::vector<char*> blobs;
    ~ChunkArena() {
        for (char* b : blobs) std::free(b);
    }
};

}  // namespace

int32_t ctx_decode_pod(
    void* p,
    const void* packed, int32_t pack_elem, int32_t code_bits,
    const uint8_t* active,
    const uint8_t* sskip,
    const void* const* score_cols, const int32_t* score_elem,
    const uint8_t* ignored,
    int32_t want_scores,
    char** out_blobs, int64_t* out_lens) {
    return decode_one(*(const Ctx*)p, packed, pack_elem, code_bits, active,
                      sskip, score_cols, score_elem, ignored, want_scores,
                      out_blobs, out_lens);
}

// One call per replay chunk; the GIL is released for the whole call.
//
//   c:            pods in this range
//   packed:       [c, N] packed first-fail words, C-contiguous
//   active_rows:  [c, F] uint8 plugin-ran masks (per-pod rows)
//   sskip_rows:   [c, S] uint8 score-skip masks
//   col_base:     [S] pointer to pod 0's raw column (NULL when unused)
//   col_stride:   [S] BYTES between consecutive pods' columns
//   col_elem:     [S] column element size (1/2/4/8, signed)
//   ignored:      [c, N] TSP score-ignore rows, or NULL
//   want_scores:  [c] uint8, feasible_count > 1
//   skip_pod:     [c] uint8 (or NULL): 1 = leave the pod's slots 0 —
//                 Python's prefilter-reject early-out owns it
//   n_threads:    workers incl. the caller (clamped to [1, 16])
//   out_ptrs/out_lens: [c*3] blob addresses/lengths (0 = absent); valid
//                 until chunk_arena_free of the returned arena
//   thread_seconds: out, summed worker busy time (tracer counter)
void* ctx_decode_chunk(
    void* p,
    int32_t c,
    const void* packed, int32_t pack_elem, int32_t code_bits,
    const uint8_t* active_rows,
    const uint8_t* sskip_rows,
    const void* const* col_base,
    const int64_t* col_stride,
    const int32_t* col_elem,
    const uint8_t* ignored,
    const uint8_t* want_scores,
    const uint8_t* skip_pod,
    int32_t n_threads,
    int64_t* out_ptrs,
    int64_t* out_lens,
    double* thread_seconds) {
    const Ctx& ctx = *(const Ctx*)p;
    const int32_t n = ctx.n, f = ctx.f, s = ctx.s;
    ChunkArena* arena = new ChunkArena();
    arena->blobs.reserve((size_t)c * 3);
    std::memset(out_ptrs, 0, (size_t)c * 3 * sizeof(int64_t));
    std::memset(out_lens, 0, (size_t)c * 3 * sizeof(int64_t));

    if (n_threads < 1) n_threads = 1;
    if (n_threads > 16) n_threads = 16;
    if (c < 2 * n_threads) n_threads = 1;  // not worth waking the pool

    std::atomic<int32_t> next{0};
    std::atomic<long long> busy_ns{0};
    std::mutex merge_m;

    auto work = [&](int) {
        auto t0 = std::chrono::steady_clock::now();
        std::vector<char*> local;
        std::vector<const void*> cols((size_t)(s > 0 ? s : 1), nullptr);
        for (;;) {
            int32_t i = next.fetch_add(1, std::memory_order_relaxed);
            if (i >= c) break;
            if (skip_pod && skip_pod[i]) continue;
            for (int32_t q = 0; q < s; ++q)
                cols[q] = col_base[q]
                    ? (const char*)col_base[q] + (int64_t)i * col_stride[q]
                    : nullptr;
            char* blobs[3];
            int64_t lens[3];
            decode_one(ctx,
                       (const char*)packed + (size_t)i * n * pack_elem,
                       pack_elem, code_bits,
                       active_rows + (size_t)i * f,
                       sskip_rows + (size_t)i * s,
                       cols.data(), col_elem,
                       ignored ? ignored + (size_t)i * n : nullptr,
                       want_scores[i] ? 1 : 0,
                       blobs, lens);
            for (int b = 0; b < 3; ++b) {
                if (!blobs[b]) continue;
                // emit caps are upper bounds (21 bytes per numeric
                // field); trim so the arena holds ~actual blob bytes
                // for the whole chunk, not the slack
                char* t = (char*)std::realloc(blobs[b], (size_t)lens[b] + 1);
                if (t) blobs[b] = t;
                local.push_back(blobs[b]);
                out_ptrs[(size_t)i * 3 + b] = (int64_t)(intptr_t)blobs[b];
                out_lens[(size_t)i * 3 + b] = lens[b];
            }
        }
        busy_ns.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0).count());
        std::lock_guard<std::mutex> lg(merge_m);
        arena->blobs.insert(arena->blobs.end(), local.begin(), local.end());
    };

    decode_pool().run(n_threads, work);
    if (thread_seconds) *thread_seconds = busy_ns.load() / 1e9;
    return arena;
}

void chunk_arena_free(void* a) { delete (ChunkArena*)a; }

char* ctx_encode_scores(void* p, const int64_t* values,
                        const uint8_t* sskip, const uint8_t* feasible,
                        int64_t* out_len) {
    const Ctx& ctx = *(const Ctx*)p;
    const int32_t n = ctx.n, s = ctx.s;
    // prefix[k] = ('{'|',') + `"Name":"` for each active scorer in name
    // order; per node the varying bytes are just the score digits.
    std::vector<std::string> prefix;
    std::vector<const int64_t*> col;
    prefix.reserve(s);
    col.reserve(s);
    size_t row_fixed = 3;
    for (int32_t k = 0; k < s; ++k) {
        int32_t q = ctx.sorted_scores[k];
        if (sskip[q]) continue;
        std::string pre(col.empty() ? "{" : ",");
        pre += ctx.score_key[q];
        pre.push_back('"');
        row_fixed += pre.size() + 21;  // prefix + digits(<=20) + closing quote
        prefix.push_back(std::move(pre));
        col.push_back(values + (size_t)q * n);
    }
    size_t cap = 3 + (col.empty() ? 0 : ctx.sum_node_key + (size_t)n * (1 + row_fixed));
    char* buf = (char*)std::malloc(cap);
    char* w = buf;
    *w++ = '{';
    bool first_node = true;
    if (!col.empty()) {
        for (int32_t si = 0; si < n; ++si) {
            int32_t j = ctx.sorted_nodes[si];
            if (!feasible[j]) continue;
            if (!first_node) *w++ = ',';
            first_node = false;
            put(w, ctx.node_key[j]);
            for (size_t k = 0; k < col.size(); ++k) {
                put(w, prefix[k]);
                auto r = std::to_chars(w, w + 24, (long long)col[k][j]);
                w = r.ptr;
                *w++ = '"';
            }
            *w++ = '}';
        }
    }
    *w++ = '}';
    *w = 0;
    *out_len = (int64_t)(w - buf);
    return buf;
}

}  // extern "C"
