// Native annotation codec — the host-side hot path of the reflector.
//
// The reference serializes scheduling results to Pod annotations in Go
// (simulator/scheduler/plugin/resultstore/store.go:133-198); at 10k pods x
// 5k nodes the filter/score/finalscore JSON blobs dominate host time in
// this framework's write-back path, so they are encoded here in C++ and
// exposed over a C ABI consumed via ctypes (no pybind11 in this image).
//
// Encoding contract (byte-identical to Go encoding/json):
//   * compact (no spaces), map keys sorted lexicographically (Go sorts
//     map keys when marshaling);
//   * strings escaped per encoding/json: ", \\, control chars, and the
//     HTML-safe set < > & as < > &;
//   * filter map reproduces the framework's stop-at-first-fail truncation:
//     plugins in execution order until the first failure, keys sorted in
//     the output object.
//
// Message resolution is table-driven: per filter plugin a LUT indexed by
// (code-1), either shared across nodes or per-node (taint messages embed
// the node's taint key/value).  Python builds the LUTs once per compiled
// workload.

#include <cstdint>
#include <charconv>
#include <cstring>
#include <cstdlib>
#include <string>
#include <algorithm>
#include <vector>

namespace {

// one string VALUE, quotes included — Python json.dumps(ensure_ascii=
// False) escapes (incl. the \b/\f shortcuts) plus Go's HTML escaping of
// < > & , matching store/annotations.py marshal() byte-for-byte
void append_escaped_n(std::string& out, const char* s, size_t len) {
    out.push_back('"');
    for (size_t i = 0; i < len; ++i) {
        unsigned char c = (unsigned char)s[i];
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\b': out += "\\b"; break;
            case '\f': out += "\\f"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            case '<': out += "\\u003c"; break;
            case '>': out += "\\u003e"; break;
            case '&': out += "\\u0026"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out.push_back((char)c);
                }
        }
    }
    out.push_back('"');
}

void append_escaped(std::string& out, const char* s) {
    append_escaped_n(out, s, std::strlen(s));
}

char* dup_string(const std::string& s) {
    char* out = (char*)std::malloc(s.size() + 1);
    std::memcpy(out, s.c_str(), s.size() + 1);
    return out;
}

// quoted integer without snprintf (the per-value %lld dominated the
// score-blob encode time at cluster scale: ~3 ms -> ~0.3 ms per blob)
void append_quoted_int(std::string& out, long long v) {
    char tmp[24];
    auto r = std::to_chars(tmp, tmp + sizeof tmp, v);
    out.push_back('"');
    out.append(tmp, (size_t)(r.ptr - tmp));
    out.push_back('"');
}

}  // namespace

extern "C" {

void codec_free(char* p) { std::free(p); }

// {"key":"value",...} from pre-sorted keys — the result-history record
// encoder (values are whole annotation blobs, so the escape pass over
// hundreds of KiB is the hot part; byte-identical to marshal(dict))
char* encode_string_map(const char* const* keys,
                        const char* const* vals,
                        const long long* val_lens,
                        long long n) {
    size_t cap = 2;
    for (long long i = 0; i < n; ++i) cap += (size_t)val_lens[i] + 48;
    std::string out;
    out.reserve(cap);
    out.push_back('{');
    for (long long i = 0; i < n; ++i) {
        if (i) out.push_back(',');
        append_escaped(out, keys[i]);
        out.push_back(':');
        append_escaped_n(out, vals[i], (size_t)val_lens[i]);
    }
    out.push_back('}');
    return dup_string(out);
}

// filter-result: {"node":{"Plugin":"passed"|msg,...},...}
//
// codes:        [F*N] int32, 0 == pass (plugin-skip already zeroed)
// active:       [F] uint8 — plugins whose Filter ran for this pod
// sorted_nodes: [N] int32 — node indices in lexicographic name order
// sorted_plugins_by_name: [F] int32 — plugin indices sorted by name
// lut_flat/lut_off: message LUTs; for plugin f the LUT spans
//     lut_flat[lut_off[f] .. lut_off[f+1]) ; node-dependent plugins
//     (per_node[f] != 0) use stride = (lut_off[f+1]-lut_off[f])/N per node.
char* encode_filter_result(
    int32_t n, int32_t f,
    const int32_t* codes,
    const uint8_t* active,
    const char* const* node_names,
    const char* const* plugin_names,
    const int32_t* sorted_nodes,
    const int32_t* sorted_plugins_by_name,
    const char* const* lut_flat,
    const int32_t* lut_off,
    const uint8_t* per_node) {
    std::string out;
    out.reserve((size_t)n * 64);
    out.push_back('{');
    bool any_active = false;
    for (int32_t pf = 0; pf < f; ++pf) any_active |= (bool)active[pf];
    bool first_node = true;
    for (int32_t si = 0; si < n && any_active; ++si) {
        int32_t j = sorted_nodes[si];
        // index (in execution order) of the first failing active plugin
        int32_t fail_at = f;
        for (int32_t pf = 0; pf < f; ++pf) {
            if (active[pf] && codes[(size_t)pf * n + j] != 0) { fail_at = pf; break; }
        }
        if (!first_node) out.push_back(',');
        first_node = false;
        append_escaped(out, node_names[j]);
        out.push_back(':');
        out.push_back('{');
        // entries: active plugins with index <= fail_at, sorted by name
        bool first_plugin = true;
        for (int32_t k = 0; k < f; ++k) {
            int32_t pf = sorted_plugins_by_name[k];
            if (!active[pf] || pf > fail_at) continue;
            const char* msg;
            int32_t code = codes[(size_t)pf * n + j];
            if (code == 0) {
                msg = "passed";
            } else {
                int32_t span = lut_off[pf + 1] - lut_off[pf];
                int32_t base = lut_off[pf];
                if (per_node[pf]) {
                    int32_t stride = span / n;
                    msg = lut_flat[base + (size_t)j * stride + (code - 1)];
                } else {
                    msg = lut_flat[base + (code - 1)];
                }
            }
            if (!first_plugin) out.push_back(',');
            first_plugin = false;
            append_escaped(out, plugin_names[pf]);
            out.push_back(':');
            append_escaped(out, msg);
        }
        out.push_back('}');
    }
    out.push_back('}');
    return dup_string(out);
}

// score-result / finalscore-result: {"node":{"Plugin":"<int>",...},...}
// over feasible nodes only; plugins with sskip are omitted.  Values are
// int64 (upstream node scores are int64; custom plugins can exceed int32).
char* encode_score_result(
    int32_t n, int32_t s,
    const int64_t* values,           // [S*N]
    const uint8_t* sskip,            // [S]
    const uint8_t* feasible,         // [N]
    const char* const* node_names,
    const char* const* score_names,
    const int32_t* sorted_nodes,
    const int32_t* sorted_scores_by_name) {
    std::string out;
    out.reserve((size_t)n * 48);
    out.push_back('{');
    bool first_node = true;
    for (int32_t si = 0; si < n; ++si) {
        int32_t j = sorted_nodes[si];
        if (!feasible[j]) continue;
        bool any = false;
        for (int32_t q = 0; q < s; ++q) if (!sskip[q]) { any = true; break; }
        if (!any) continue;
        if (!first_node) out.push_back(',');
        first_node = false;
        append_escaped(out, node_names[j]);
        out.push_back(':');
        out.push_back('{');
        bool first_sc = true;
        for (int32_t k = 0; k < s; ++k) {
            int32_t q = sorted_scores_by_name[k];
            if (sskip[q]) continue;
            if (!first_sc) out.push_back(',');
            first_sc = false;
            append_escaped(out, score_names[q]);
            out.push_back(':');
            append_quoted_int(out, (long long)values[(size_t)q * n + j]);
        }
        out.push_back('}');
    }
    out.push_back('}');
    return dup_string(out);
}

}  // extern "C"
