"""ctypes loader for the native annotation codec.

Builds annotation_codec.cpp with g++ on first use (cached next to the
source); falls back to the pure-Python encoder when the toolchain is
unavailable.  See annotation_codec.cpp for the encoding contract.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False


BUILD_CMD = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread"]

# `make native-asan` recipe: a sanitizer build of the same source for the
# slow codec-suite-under-ASan test (tests/test_native_asan.py)
ASAN_FLAGS = ["-g", "-fsanitize=address,undefined",
              "-fno-sanitize-recover=undefined"]

# `make native-tsan` recipe: ThreadSanitizer build for the concurrent
# chunk-decode soak (tests/test_native_tsan.py) — the codec's worker
# pool, per-call arenas and cross-chunk FilterCaches are exactly the
# kind of hand-rolled concurrency TSan exists for
TSAN_FLAGS = ["-g", "-fsanitize=thread"]


def build_codec(so: str | None = None,
                extra_flags: list[str] | tuple[str, ...] = ()) -> str:
    """Compile annotation_codec.cpp -> _annotation_codec.so (the recipe
    `make codec` runs); returns the .so path."""
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "annotation_codec.cpp")
    so = so or os.path.join(here, "_annotation_codec.so")
    subprocess.run([*BUILD_CMD, *extra_flags, "-o", so, src], check=True,
                   capture_output=True)
    return so


def _build_and_load():
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "annotation_codec.cpp")
    # KSS_TPU_NATIVE_SO points the loader at a prebuilt library (the
    # sanitizer harness runs the suite against the ASan build this way);
    # no rebuild-if-stale in that mode — the harness owns the artifact
    override = os.environ.get("KSS_TPU_NATIVE_SO")
    if override:
        lib = ctypes.CDLL(override)
    else:
        so = os.path.join(here, "_annotation_codec.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            build_codec(so)
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # stale or foreign-platform binary: rebuild from source
            build_codec(so)
            lib = ctypes.CDLL(so)
    P = ctypes.POINTER
    lib.encode_filter_result.restype = ctypes.c_void_p
    lib.encode_filter_result.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32),
        P(ctypes.c_char_p), P(ctypes.c_int32), P(ctypes.c_uint8),
    ]
    lib.encode_score_result.restype = ctypes.c_void_p
    lib.encode_score_result.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_int64), P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32),
    ]
    lib.codec_free.restype = None
    lib.codec_free.argtypes = [ctypes.c_void_p]
    lib.encode_string_map.restype = ctypes.c_void_p
    lib.encode_string_map.argtypes = [
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_longlong), ctypes.c_longlong,
    ]
    lib.encode_string_map_sized.restype = ctypes.c_void_p
    lib.encode_string_map_sized.argtypes = [
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_longlong), ctypes.c_longlong,
        P(ctypes.c_longlong), P(ctypes.c_int32),
    ]
    lib.codec_ctx_new.restype = ctypes.c_void_p
    lib.codec_ctx_new.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_char_p), P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32), P(ctypes.c_int32),
        P(ctypes.c_char_p), P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_int32), P(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.ctx_decode_pod.restype = ctypes.c_int32
    lib.ctx_decode_pod.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_void_p), P(ctypes.c_int32),
        P(ctypes.c_uint8),
        ctypes.c_int32,
        P(ctypes.c_void_p), P(ctypes.c_int64),
    ]
    lib.ctx_decode_chunk.restype = ctypes.c_void_p
    lib.ctx_decode_chunk.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_void_p), P(ctypes.c_int64), P(ctypes.c_int32),
        P(ctypes.c_uint8), P(ctypes.c_uint8), P(ctypes.c_uint8),
        ctypes.c_int32,
        P(ctypes.c_int64), P(ctypes.c_int64),
        P(ctypes.c_double),
    ]
    lib.chunk_arena_free.restype = None
    lib.chunk_arena_free.argtypes = [ctypes.c_void_p]
    lib.codec_ctx_free.restype = None
    lib.codec_ctx_free.argtypes = [ctypes.c_void_p]
    lib.ctx_all_ascii.restype = ctypes.c_int32
    lib.ctx_all_ascii.argtypes = [ctypes.c_void_p]
    lib.ctx_encode_filter.restype = ctypes.c_void_p
    lib.ctx_encode_filter.argtypes = [
        ctypes.c_void_p, P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_int64)]
    lib.ctx_encode_scores.restype = ctypes.c_void_p
    lib.ctx_encode_scores.argtypes = [
        ctypes.c_void_p, P(ctypes.c_int64), P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_int64)]
    return lib


# str straight from the C buffer: PyUnicode_DecodeUTF8 builds the
# (compact-ASCII) str object in ONE copy, where string_at(...).decode()
# would materialize an intermediate bytes object first — at ~1.3 MB of
# JSON per pod the extra pass is real memory traffic on the decode path
try:
    _PyUnicode_DecodeUTF8 = ctypes.pythonapi.PyUnicode_DecodeUTF8
    _PyUnicode_DecodeUTF8.restype = ctypes.py_object
    _PyUnicode_DecodeUTF8.argtypes = [
        ctypes.c_void_p, ctypes.c_ssize_t, ctypes.c_char_p]
except (AttributeError, OSError):  # non-CPython / no libpython symbols:
    _PyUnicode_DecodeUTF8 = None   # keep the module's graceful fallback


def take_sized_string(lib, ptr, length: int) -> str:
    """One-copy str from a codec-allocated buffer of known length; frees
    the buffer."""
    try:
        if _PyUnicode_DecodeUTF8 is not None:
            return _PyUnicode_DecodeUTF8(ptr, length, b"strict")
        return ctypes.string_at(ptr, length).decode()
    finally:
        lib.codec_free(ptr)


# ASCII fast path: when the codec context proves every emitted byte is
# ASCII (ctx_all_ascii), the str can be built by PyUnicode_New + memmove —
# a plain vectorized copy instead of DecodeUTF8's validating scan.  The
# data offset of a compact-ASCII str is derived at runtime
# (sys.getsizeof("") counts PyASCIIObject + the NUL) and the whole path is
# self-tested once at import; any surprise falls back to the decode path.
_ASCII_TAKE_OK = False
try:
    import sys as _sys

    _PyUnicode_New = ctypes.pythonapi.PyUnicode_New
    _PyUnicode_New.restype = ctypes.py_object
    _PyUnicode_New.argtypes = [ctypes.c_ssize_t, ctypes.c_uint32]
    _ASCII_DATA_OFF = _sys.getsizeof("") - 1

    def _ascii_take(ptr, length: int) -> str:
        if length == 0:
            return ""  # PyUnicode_New(0, ...) returns the shared singleton
        s = _PyUnicode_New(length, 127)
        # copy exactly `length` bytes: PyUnicode_New already wrote the
        # NUL terminator at data[length], so the source needn't be
        # NUL-terminated (the old length+1 memmove silently imposed that
        # on every C buffer crossing this boundary — and read one byte
        # past buffers that weren't)
        ctypes.memmove(id(s) + _ASCII_DATA_OFF, ptr, length)
        return s

    # probe with trailing GARBAGE (not NUL) after the payload: proves both
    # the content copy and that PyUnicode_New supplied the terminator
    _probe = b"probe{\"x\":\"1\"}"
    _buf = (ctypes.c_char * (len(_probe) + 1)).from_buffer_copy(_probe + b"X")
    _out = _ascii_take(ctypes.addressof(_buf), len(_probe))
    _ASCII_TAKE_OK = (
        _out == _probe.decode()
        and ctypes.string_at(id(_out) + _ASCII_DATA_OFF, len(_probe) + 1)
        == _probe + b"\x00")
except Exception:
    _ASCII_TAKE_OK = False


def take_sized_string_ascii(lib, ptr, length: int) -> str:
    """take_sized_string for buffers PROVEN pure-ASCII by the codec ctx."""
    if not _ASCII_TAKE_OK:
        return take_sized_string(lib, ptr, length)
    try:
        return _ascii_take(ptr, length)
    finally:
        lib.codec_free(ptr)


# Arena string takers — str from an (address, length) pair WITHOUT
# freeing: ctx_decode_chunk's blobs live in a per-call arena released by
# ONE chunk_arena_free after every pod's strs are built, so the takers
# only copy.  peek_string_ascii is the plain-memcpy path for contexts
# proven pure-ASCII; peek_string is the UTF-8-validating fallback.

def peek_string(addr: int, length: int) -> str:
    if _PyUnicode_DecodeUTF8 is not None:
        return _PyUnicode_DecodeUTF8(addr, length, b"strict")
    return ctypes.string_at(addr, length).decode()


def peek_string_ascii(addr: int, length: int) -> str:
    if not _ASCII_TAKE_OK:
        return peek_string(addr, length)
    return _ascii_take(addr, length)


def get_lib():
    """The loaded codec, or None when native build is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is None and not _tried:
            _tried = True
            try:
                # the g++ build/dlopen runs under the module lock ON
                # PURPOSE: concurrent first users must block until the
                # one-shot build lands rather than race the compiler
                _lib = _build_and_load()  # kss-analyze: allow(blocking-under-lock)
            except Exception:
                _lib = None
    return _lib


def take_string(lib, ptr) -> str:
    """Copy a codec-allocated C string and free it."""
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.codec_free(ptr)
