"""ctypes loader for the native annotation codec.

Builds annotation_codec.cpp with g++ on first use (cached next to the
source); falls back to the pure-Python encoder when the toolchain is
unavailable.  See annotation_codec.cpp for the encoding contract.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False


BUILD_CMD = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]


def build_codec(so: str | None = None) -> str:
    """Compile annotation_codec.cpp -> _annotation_codec.so (the recipe
    `make codec` runs); returns the .so path."""
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "annotation_codec.cpp")
    so = so or os.path.join(here, "_annotation_codec.so")
    subprocess.run([*BUILD_CMD, "-o", so, src], check=True, capture_output=True)
    return so


def _build_and_load():
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "annotation_codec.cpp")
    so = os.path.join(here, "_annotation_codec.so")
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        build_codec(so)
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        # stale or foreign-platform binary: rebuild from source
        build_codec(so)
        lib = ctypes.CDLL(so)
    P = ctypes.POINTER
    lib.encode_filter_result.restype = ctypes.c_void_p
    lib.encode_filter_result.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32),
        P(ctypes.c_char_p), P(ctypes.c_int32), P(ctypes.c_uint8),
    ]
    lib.encode_score_result.restype = ctypes.c_void_p
    lib.encode_score_result.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_int64), P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32),
    ]
    lib.codec_free.restype = None
    lib.codec_free.argtypes = [ctypes.c_void_p]
    lib.encode_string_map.restype = ctypes.c_void_p
    lib.encode_string_map.argtypes = [
        P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_longlong), ctypes.c_longlong,
    ]
    lib.codec_ctx_new.restype = ctypes.c_void_p
    lib.codec_ctx_new.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_char_p), P(ctypes.c_char_p), P(ctypes.c_char_p),
        P(ctypes.c_int32), P(ctypes.c_int32), P(ctypes.c_int32),
        P(ctypes.c_char_p), P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_int32), P(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.ctx_decode_pod.restype = ctypes.c_int32
    lib.ctx_decode_pod.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_void_p), P(ctypes.c_int32),
        P(ctypes.c_uint8),
        ctypes.c_int32,
        P(ctypes.c_void_p), P(ctypes.c_int64),
    ]
    lib.codec_ctx_free.restype = None
    lib.codec_ctx_free.argtypes = [ctypes.c_void_p]
    lib.ctx_encode_filter.restype = ctypes.c_void_p
    lib.ctx_encode_filter.argtypes = [
        ctypes.c_void_p, P(ctypes.c_int32), P(ctypes.c_uint8),
        P(ctypes.c_int64)]
    lib.ctx_encode_scores.restype = ctypes.c_void_p
    lib.ctx_encode_scores.argtypes = [
        ctypes.c_void_p, P(ctypes.c_int64), P(ctypes.c_uint8), P(ctypes.c_uint8),
        P(ctypes.c_int64)]
    return lib


# str straight from the C buffer: PyUnicode_DecodeUTF8 builds the
# (compact-ASCII) str object in ONE copy, where string_at(...).decode()
# would materialize an intermediate bytes object first — at ~1.3 MB of
# JSON per pod the extra pass is real memory traffic on the decode path
try:
    _PyUnicode_DecodeUTF8 = ctypes.pythonapi.PyUnicode_DecodeUTF8
    _PyUnicode_DecodeUTF8.restype = ctypes.py_object
    _PyUnicode_DecodeUTF8.argtypes = [
        ctypes.c_void_p, ctypes.c_ssize_t, ctypes.c_char_p]
except (AttributeError, OSError):  # non-CPython / no libpython symbols:
    _PyUnicode_DecodeUTF8 = None   # keep the module's graceful fallback


def take_sized_string(lib, ptr, length: int) -> str:
    """One-copy str from a codec-allocated buffer of known length; frees
    the buffer."""
    try:
        if _PyUnicode_DecodeUTF8 is not None:
            return _PyUnicode_DecodeUTF8(ptr, length, b"strict")
        return ctypes.string_at(ptr, length).decode()
    finally:
        lib.codec_free(ptr)


def get_lib():
    """The loaded codec, or None when native build is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is None and not _tried:
            _tried = True
            try:
                _lib = _build_and_load()
            except Exception:
                _lib = None
    return _lib


def take_string(lib, ptr) -> str:
    """Copy a codec-allocated C string and free it."""
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.codec_free(ptr)
