"""Result store: per-pod scheduling results, serialized to annotations.

API parity with the reference result store (reference:
simulator/scheduler/plugin/resultstore/store.go): granular Add* methods
per extension point keyed by namespace/pod, get_stored_result() producing
the 13 annotation JSON blobs (:133-198), finalscore = normalized score x
plugin weight (:488-507, weight map semantics of plugins.go:289-304),
delete_data() (:509-520), and AddCustomResult for plugin-extender
debugging payloads (:617-626).

The tensor engine deposits whole decoded result maps via put_decoded()
(its per-pod output already IS the 13 encoded blobs); the granular
methods serve host-side escape hatches (extenders, plugin extenders) and
API compatibility.  Granular adds and decoded deposits merge: granular
values overwrite the decoded blob for the touched keys.

Lazy mode (store/lazy.py, the default on the batched wave paths): the
engine deposits a `(wave, index)` handle via put_lazy() instead of the
decoded blobs; get_stored_result() materializes the pod's chunk through
the wave's memoized chunk decode transparently — including the chunk's
device->host fetch when the wave left its results device-resident
(framework/replay.py) — and take_deferred() hands the whole entry to
the reflector as a deferred write-back so the wave's critical path
never decodes (or transfers the heavy tensors) at all.  The merge
semantics are unchanged: the lazily materialized 13 keys are the base,
decoded deposits overlay them, granular adds overlay both.
"""

from __future__ import annotations

import json
import threading

from . import annotations as ann

PASSED = ann.PASSED_FILTER_MESSAGE
SUCCESS = ann.SUCCESS_MESSAGE


def _key(namespace: str, pod_name: str) -> str:
    return f"{namespace}/{pod_name}"


_FIELDS = (
    "selected_node", "pre_score", "score", "final_score",
    "pre_filter_status", "pre_filter_result", "filter", "post_filter",
    "permit", "permit_timeout", "reserve", "prebind", "bind",
    "custom", "decoded", "lazy",
)


class _Result:
    __slots__ = _FIELDS

    def __init__(self):
        self.selected_node = ""
        self.pre_score: dict[str, str] = {}
        self.score: dict[str, dict[str, str]] = {}
        self.final_score: dict[str, dict[str, str]] = {}
        self.pre_filter_status: dict[str, str] = {}
        self.pre_filter_result: dict[str, list[str]] = {}
        self.filter: dict[str, dict[str, str]] = {}
        self.post_filter: dict[str, dict[str, str]] = {}
        self.permit: dict[str, str] = {}
        self.permit_timeout: dict[str, str] = {}
        self.reserve: dict[str, str] = {}
        self.prebind: dict[str, str] = {}
        self.bind: dict[str, str] = {}
        self.custom: dict[str, str] = {}
        self.decoded: dict[str, str] = {}
        # (LazyWave, pod index) handle — the wave's tensors stand in for
        # the 13 decoded blobs until a read materializes them
        self.lazy: tuple | None = None


class _Snapshot:
    """Reference snapshot of one _Result taken under the store lock —
    the O(keys) copy get_stored_result pays while holding _mu; the JSON
    decode/merge/encode of the (potentially ~MB) blobs runs on this
    detached view after release (the PR 2 encode-off-the-store-lock
    rule, enforced by kss-analyze serialize-under-lock)."""

    __slots__ = _FIELDS

    def __init__(self, r: _Result):
        def snap2(d):
            # two-level snapshot: granular adds mutate the inner
            # per-node dicts in place, so sharing them outside the lock
            # would race the marshal in _merge_snapshot
            return {node: dict(plugins) for node, plugins in d.items()}

        self.decoded = dict(r.decoded)
        self.lazy = r.lazy
        self.pre_filter_result = {p: list(v)
                                  for p, v in r.pre_filter_result.items()}
        self.pre_filter_status = dict(r.pre_filter_status)
        self.filter = snap2(r.filter)
        self.post_filter = snap2(r.post_filter)
        self.pre_score = dict(r.pre_score)
        self.score = snap2(r.score)
        self.final_score = snap2(r.final_score)
        self.reserve = dict(r.reserve)
        self.permit = dict(r.permit)
        self.permit_timeout = dict(r.permit_timeout)
        self.prebind = dict(r.prebind)
        self.bind = dict(r.bind)
        self.custom = dict(r.custom)
        self.selected_node = r.selected_node


def _merge_snapshot(snap: _Snapshot) -> dict[str, str]:
    """The 13 annotation blobs from a snapshot: lazy-materialized base
    (one memoized chunk decode on a cold read), decoded deposits over
    it, granular adds over both — runs with NO lock held."""
    out: dict[str, str] = {}
    if snap.lazy is not None:
        wave, idx = snap.lazy
        out.update(wave.get(idx))
    out.update(snap.decoded)

    def put(key, granular, nested=False):
        """Merge granular adds OVER the decoded blob for the key:
        a custom plugin's Reserve result must not erase an
        in-tree plugin's decoded entry under the same key."""
        if not granular:
            if key not in out:
                out[key] = ann.marshal({} if not isinstance(granular, str) else "")
            return
        base = {}
        if key in out:
            try:
                base = json.loads(out[key])
            except ValueError:
                base = {}
            if not isinstance(base, dict):
                base = {}
        if nested:
            for node, plugins in granular.items():
                base.setdefault(node, {}).update(plugins)
        else:
            base.update(granular)
        out[key] = ann.marshal(base)

    put(ann.PRE_FILTER_RESULT, snap.pre_filter_result)
    put(ann.PRE_FILTER_STATUS_RESULT, snap.pre_filter_status)
    put(ann.FILTER_RESULT, snap.filter, nested=True)
    put(ann.POST_FILTER_RESULT, snap.post_filter, nested=True)
    put(ann.PRE_SCORE_RESULT, snap.pre_score)
    put(ann.SCORE_RESULT, snap.score, nested=True)
    put(ann.FINAL_SCORE_RESULT, snap.final_score, nested=True)
    put(ann.RESERVE_RESULT, snap.reserve)
    put(ann.PERMIT_STATUS_RESULT, snap.permit)
    put(ann.PERMIT_TIMEOUT_RESULT, snap.permit_timeout)
    put(ann.PRE_BIND_RESULT, snap.prebind)
    put(ann.BIND_RESULT, snap.bind)
    if snap.selected_node or ann.SELECTED_NODE not in out:
        out[ann.SELECTED_NODE] = snap.selected_node
    out.update(snap.custom)
    return out


class DeferredResult:
    """A consumed result-store entry whose materialization is deferred:
    the reflector queues these (store/lazy.py LazyReflections) instead
    of decoding on the wave's critical path; result_set() runs the same
    merge get_stored_result would have."""

    __slots__ = ("_snap",)

    def __init__(self, snap: _Snapshot):
        self._snap = snap

    def ready(self) -> bool:
        """True once materialization cannot block: the backing wave is
        sealed (or there is no lazy part).  Drains skip unready records
        — they belong to the in-flight wave's timeline, and applying
        them would stall the reader until the replay finishes."""
        lazy = self._snap.lazy
        return lazy is None or getattr(lazy[0], "sealed", True)

    def result_set(self) -> dict[str, str]:
        return _merge_snapshot(self._snap)


class ResultStore:
    def __init__(self, score_plugin_weight: dict[str, int] | None = None):
        self._mu = threading.Lock()
        self._results: dict[str, _Result] = {}
        self.score_plugin_weight = score_plugin_weight or {}

    def _get(self, namespace: str, pod_name: str) -> _Result:
        k = _key(namespace, pod_name)
        if k not in self._results:
            self._results[k] = _Result()
        return self._results[k]

    # ------------------------------------------------------------ adds

    def put_decoded(self, namespace: str, pod_name: str, annotations: dict[str, str]):
        with self._mu:
            r = self._get(namespace, pod_name)
            shadowed = False
            if ann.SELECTED_NODE in annotations:
                # a full-cycle deposit (every cycle's 13 keys include
                # selected-node, "" when unschedulable) fully shadows a
                # leftover lazy handle — drop it so it stops pinning the
                # old wave's replay buffers and costing a dead chunk
                # decode on read; partial overlays (the extender-bind
                # record) keep the base
                shadowed = r.lazy is not None
                r.lazy = None
            r.decoded.update(annotations)
        if shadowed:
            # an UNREAD wave's results just vanished behind a newer
            # cycle — rare (a pod re-scheduled before anyone read it),
            # and exactly the evidence loss a post-mortem should show
            from ..utils.blackbox import BLACKBOX

            BLACKBOX.record("result.lazy_shadowed",
                            pod=_key(namespace, pod_name), by="decoded")

    def has_result(self, pod: dict) -> bool:
        """True when an entry exists for the pod — the informer's cheap
        existence check, guaranteed never to materialize a lazy handle
        (get_stored_result would decode the pod's chunk)."""
        meta = pod.get("metadata") or {}
        k = _key(meta.get("namespace") or "default", meta.get("name", ""))
        with self._mu:
            return k in self._results

    def put_lazy(self, namespace: str, pod_name: str, wave, index: int):
        """Deposit a lazy handle: `wave.get(index)` yields the pod's 13
        decoded blobs on first read (store/lazy.py LazyWave).  Replaces
        any previous cycle's deposit, like a full put_decoded would;
        later put_decoded / granular adds overlay it."""
        with self._mu:
            r = self._get(namespace, pod_name)
            shadowed = r.lazy is not None
            r.lazy = (wave, index)
            r.decoded = {}
        if shadowed:
            # only the rare cross-wave overwrite records (never the
            # per-pod hot path: fresh entries have no handle to shadow)
            from ..utils.blackbox import BLACKBOX

            BLACKBOX.record("result.lazy_shadowed",
                            pod=_key(namespace, pod_name), by="lazy")

    def add_filter_result(self, namespace, pod_name, node_name, plugin_name, reason):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.filter.setdefault(node_name, {})[plugin_name] = reason

    def add_post_filter_result(self, namespace, pod_name, nominated_node_name,
                               plugin_name, node_names):
        with self._mu:
            r = self._get(namespace, pod_name)
            for node_name in node_names:
                r.post_filter.setdefault(node_name, {})
                if node_name == nominated_node_name:
                    r.post_filter[node_name][plugin_name] = ann.POST_FILTER_NOMINATED_MESSAGE

    def add_score_result(self, namespace, pod_name, node_name, plugin_name, score: int):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.score.setdefault(node_name, {})[plugin_name] = str(int(score))
            self._add_normalized_locked(r, node_name, plugin_name, score)

    def add_normalized_score_result(self, namespace, pod_name, node_name,
                                    plugin_name, normalized_score: int):
        with self._mu:
            r = self._get(namespace, pod_name)
            self._add_normalized_locked(r, node_name, plugin_name, normalized_score)

    def _add_normalized_locked(self, r: _Result, node_name, plugin_name, score: int):
        weight = self.score_plugin_weight.get(plugin_name, 0)
        r.final_score.setdefault(node_name, {})[plugin_name] = str(int(score) * int(weight))

    def add_pre_filter_result(self, namespace, pod_name, plugin_name, reason,
                              pre_filter_result=None):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.pre_filter_status[plugin_name] = reason
            if pre_filter_result is not None:
                r.pre_filter_result[plugin_name] = list(pre_filter_result)

    def add_pre_score_result(self, namespace, pod_name, plugin_name, reason):
        with self._mu:
            self._get(namespace, pod_name).pre_score[plugin_name] = reason

    def add_permit_result(self, namespace, pod_name, plugin_name, status, timeout: str):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.permit[plugin_name] = status
            r.permit_timeout[plugin_name] = timeout

    def add_selected_node(self, namespace, pod_name, node_name):
        with self._mu:
            self._get(namespace, pod_name).selected_node = node_name

    def add_reserve_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).reserve[plugin_name] = status

    def add_bind_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).bind[plugin_name] = status

    def add_pre_bind_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).prebind[plugin_name] = status

    def add_custom_result(self, namespace, pod_name, annotation_key, result):
        with self._mu:
            self._get(namespace, pod_name).custom[annotation_key] = result

    # ------------------------------------------------------------ read/delete

    def get_stored_result(self, pod: dict) -> dict[str, str] | None:
        meta = pod.get("metadata") or {}
        k = _key(meta.get("namespace") or "default", meta.get("name", ""))
        with self._mu:
            r = self._results.get(k)
            if r is None:
                return None
            snap = _Snapshot(r)
        # merge (and any lazy chunk decode) runs after release so
        # concurrent granular adds and the engine's deposits never
        # queue behind serialization
        return _merge_snapshot(snap)

    def take_deferred(self, namespace: str, pod_name: str) -> DeferredResult | None:
        """Consume a LAZY entry as a deferred write-back: the snapshot
        is taken and the entry removed (the delete-after-reflect
        contract) without materializing anything — the reflector queues
        the DeferredResult and a later read pays the decode.  Entries
        without a lazy handle return None; the caller reflects them
        eagerly as before."""
        k = _key(namespace or "default", pod_name)
        with self._mu:
            r = self._results.get(k)
            if r is None or r.lazy is None:
                return None
            snap = _Snapshot(r)
            del self._results[k]
        return DeferredResult(snap)

    def delete_data(self, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        with self._mu:
            self._results.pop(_key(meta.get("namespace") or "default", meta.get("name", "")), None)
