"""Result store: per-pod scheduling results, serialized to annotations.

API parity with the reference result store (reference:
simulator/scheduler/plugin/resultstore/store.go): granular Add* methods
per extension point keyed by namespace/pod, get_stored_result() producing
the 13 annotation JSON blobs (:133-198), finalscore = normalized score x
plugin weight (:488-507, weight map semantics of plugins.go:289-304),
delete_data() (:509-520), and AddCustomResult for plugin-extender
debugging payloads (:617-626).

The tensor engine deposits whole decoded result maps via put_decoded()
(its per-pod output already IS the 13 encoded blobs); the granular
methods serve host-side escape hatches (extenders, plugin extenders) and
API compatibility.  Granular adds and decoded deposits merge: granular
values overwrite the decoded blob for the touched keys.
"""

from __future__ import annotations

import json
import threading

from . import annotations as ann

PASSED = ann.PASSED_FILTER_MESSAGE
SUCCESS = ann.SUCCESS_MESSAGE


def _key(namespace: str, pod_name: str) -> str:
    return f"{namespace}/{pod_name}"


class _Result:
    __slots__ = (
        "selected_node", "pre_score", "score", "final_score",
        "pre_filter_status", "pre_filter_result", "filter", "post_filter",
        "permit", "permit_timeout", "reserve", "prebind", "bind",
        "custom", "decoded",
    )

    def __init__(self):
        self.selected_node = ""
        self.pre_score: dict[str, str] = {}
        self.score: dict[str, dict[str, str]] = {}
        self.final_score: dict[str, dict[str, str]] = {}
        self.pre_filter_status: dict[str, str] = {}
        self.pre_filter_result: dict[str, list[str]] = {}
        self.filter: dict[str, dict[str, str]] = {}
        self.post_filter: dict[str, dict[str, str]] = {}
        self.permit: dict[str, str] = {}
        self.permit_timeout: dict[str, str] = {}
        self.reserve: dict[str, str] = {}
        self.prebind: dict[str, str] = {}
        self.bind: dict[str, str] = {}
        self.custom: dict[str, str] = {}
        self.decoded: dict[str, str] = {}


class ResultStore:
    def __init__(self, score_plugin_weight: dict[str, int] | None = None):
        self._mu = threading.Lock()
        self._results: dict[str, _Result] = {}
        self.score_plugin_weight = score_plugin_weight or {}

    def _get(self, namespace: str, pod_name: str) -> _Result:
        k = _key(namespace, pod_name)
        if k not in self._results:
            self._results[k] = _Result()
        return self._results[k]

    # ------------------------------------------------------------ adds

    def put_decoded(self, namespace: str, pod_name: str, annotations: dict[str, str]):
        with self._mu:
            self._get(namespace, pod_name).decoded.update(annotations)

    def add_filter_result(self, namespace, pod_name, node_name, plugin_name, reason):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.filter.setdefault(node_name, {})[plugin_name] = reason

    def add_post_filter_result(self, namespace, pod_name, nominated_node_name,
                               plugin_name, node_names):
        with self._mu:
            r = self._get(namespace, pod_name)
            for node_name in node_names:
                r.post_filter.setdefault(node_name, {})
                if node_name == nominated_node_name:
                    r.post_filter[node_name][plugin_name] = ann.POST_FILTER_NOMINATED_MESSAGE

    def add_score_result(self, namespace, pod_name, node_name, plugin_name, score: int):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.score.setdefault(node_name, {})[plugin_name] = str(int(score))
            self._add_normalized_locked(r, node_name, plugin_name, score)

    def add_normalized_score_result(self, namespace, pod_name, node_name,
                                    plugin_name, normalized_score: int):
        with self._mu:
            r = self._get(namespace, pod_name)
            self._add_normalized_locked(r, node_name, plugin_name, normalized_score)

    def _add_normalized_locked(self, r: _Result, node_name, plugin_name, score: int):
        weight = self.score_plugin_weight.get(plugin_name, 0)
        r.final_score.setdefault(node_name, {})[plugin_name] = str(int(score) * int(weight))

    def add_pre_filter_result(self, namespace, pod_name, plugin_name, reason,
                              pre_filter_result=None):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.pre_filter_status[plugin_name] = reason
            if pre_filter_result is not None:
                r.pre_filter_result[plugin_name] = list(pre_filter_result)

    def add_pre_score_result(self, namespace, pod_name, plugin_name, reason):
        with self._mu:
            self._get(namespace, pod_name).pre_score[plugin_name] = reason

    def add_permit_result(self, namespace, pod_name, plugin_name, status, timeout: str):
        with self._mu:
            r = self._get(namespace, pod_name)
            r.permit[plugin_name] = status
            r.permit_timeout[plugin_name] = timeout

    def add_selected_node(self, namespace, pod_name, node_name):
        with self._mu:
            self._get(namespace, pod_name).selected_node = node_name

    def add_reserve_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).reserve[plugin_name] = status

    def add_bind_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).bind[plugin_name] = status

    def add_pre_bind_result(self, namespace, pod_name, plugin_name, status):
        with self._mu:
            self._get(namespace, pod_name).prebind[plugin_name] = status

    def add_custom_result(self, namespace, pod_name, annotation_key, result):
        with self._mu:
            self._get(namespace, pod_name).custom[annotation_key] = result

    # ------------------------------------------------------------ read/delete

    def get_stored_result(self, pod: dict) -> dict[str, str] | None:
        meta = pod.get("metadata") or {}
        k = _key(meta.get("namespace") or "default", meta.get("name", ""))

        def snap2(d):
            # two-level snapshot: granular adds mutate the inner
            # per-node dicts in place, so sharing them outside the lock
            # would race the marshal below
            return {node: dict(plugins) for node, plugins in d.items()}

        with self._mu:
            r = self._results.get(k)
            if r is None:
                return None
            # the lock hold is ONLY these O(keys) reference snapshots;
            # the JSON decode/merge/encode of the (potentially ~MB)
            # blobs runs after release so concurrent granular adds and
            # the engine's put_decoded never queue behind serialization
            # (the PR 2 encode-off-the-store-lock rule, enforced by
            # kss-analyze serialize-under-lock)
            out = dict(r.decoded)
            pre_filter_result = {p: list(v)
                                 for p, v in r.pre_filter_result.items()}
            pre_filter_status = dict(r.pre_filter_status)
            filt = snap2(r.filter)
            post_filter = snap2(r.post_filter)
            pre_score = dict(r.pre_score)
            score = snap2(r.score)
            final_score = snap2(r.final_score)
            reserve = dict(r.reserve)
            permit = dict(r.permit)
            permit_timeout = dict(r.permit_timeout)
            prebind = dict(r.prebind)
            bind = dict(r.bind)
            custom = dict(r.custom)
            selected_node = r.selected_node

        def put(key, granular, nested=False):
            """Merge granular adds OVER the decoded blob for the key:
            a custom plugin's Reserve result must not erase an
            in-tree plugin's decoded entry under the same key."""
            if not granular:
                if key not in out:
                    out[key] = ann.marshal({} if not isinstance(granular, str) else "")
                return
            base = {}
            if key in out:
                try:
                    base = json.loads(out[key])
                except ValueError:
                    base = {}
                if not isinstance(base, dict):
                    base = {}
            if nested:
                for node, plugins in granular.items():
                    base.setdefault(node, {}).update(plugins)
            else:
                base.update(granular)
            out[key] = ann.marshal(base)

        put(ann.PRE_FILTER_RESULT, pre_filter_result)
        put(ann.PRE_FILTER_STATUS_RESULT, pre_filter_status)
        put(ann.FILTER_RESULT, filt, nested=True)
        put(ann.POST_FILTER_RESULT, post_filter, nested=True)
        put(ann.PRE_SCORE_RESULT, pre_score)
        put(ann.SCORE_RESULT, score, nested=True)
        put(ann.FINAL_SCORE_RESULT, final_score, nested=True)
        put(ann.RESERVE_RESULT, reserve)
        put(ann.PERMIT_STATUS_RESULT, permit)
        put(ann.PERMIT_TIMEOUT_RESULT, permit_timeout)
        put(ann.PRE_BIND_RESULT, prebind)
        put(ann.BIND_RESULT, bind)
        if selected_node or ann.SELECTED_NODE not in out:
            out[ann.SELECTED_NODE] = selected_node
        out.update(custom)
        return out

    def delete_data(self, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        with self._mu:
            self._results.pop(_key(meta.get("namespace") or "default", meta.get("name", "")), None)
