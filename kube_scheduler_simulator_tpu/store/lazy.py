"""Lazy annotation materialization: decode on first read, not per wave.

At fleet scale the wave's dominant span is `replay_and_decode_stream`
(BENCH_r05: 15.92s of a ~17s wave at 10k pods x 5k nodes) even though
every commit/bind/gang decision already comes straight from the replay
tensors — the decoded JSON blobs exist only for CONSUMERS (API reads,
the web UI, result-history), and real consumers read a handful of pods,
not all 10k.  This module makes the compact replay tensors the source
of truth and defers the three heavy per-pod blobs to first read:

  * `LazyWave` holds one committed wave's ReplayResult and materializes
    the 13-key annotation dicts per compact chunk — memoized,
    exactly-once under concurrent cold reads, one GIL-released
    `ctx_decode_chunk` call per chunk (store/decode.py ladder), so a
    single cold read pays for its whole chunk and every chunk-mate read
    after it is a dictionary lookup;
  * the result store holds `(wave, index)` handles instead of blobs
    (`ResultStore.put_lazy`) and materializes transparently inside
    `get_stored_result`;
  * the reflector defers its write-backs for lazy results
    (`StoreReflector.reflect_batch` -> `LazyReflections`), and the
    ObjectStore read hooks drain them so GET/list/watch/export of a pod
    observes exactly the eager path's bytes (docs/api.md).

Buffer lifetime (docs/wave-pipeline.md): a LazyWave pins its
ReplayResult — the per-chunk compact buffers (`rr._compact`: live
DEVICE arrays in the device-resident default, host numpy after the
first cold read or a budget spill — framework/replay.py), the
CompiledWorkload's host tables (skip masks, prefilter rejects, message
LUT context) and the node table — across the wave boundary until every
holder of a handle is read, overwritten or deleted.  All of that state
is written once by the wave and never mutated afterwards (later waves
build fresh CompiledWorkloads; `NodeTableReuse` shares only the
immutable node table), so deferred decode is bit-identical to eager
decode of the same wave; a cold read first performs the chunk's
memoized D2H (`d2h_fetch` span under `decode_lazy`), then the one
GIL-released chunk decode.  `KSS_TPU_EAGER_DECODE=1` disables deferral
engine-wide (the golden/parity baseline); `KSS_TPU_HOST_RESIDENT=1`
keeps the lazy decode but fetches the compact tensors to host in-wave
(the PR 9 behavior, the middle parity rung).
"""

from __future__ import annotations

import threading
import time

from ..utils.tracing import TRACER

# chunk granularity when the ReplayResult holds full arrays (the
# speculative path) instead of compact chunks
_FALLBACK_CHUNK = 512


class LazyWave:
    """One committed wave's deferred annotations.

    Thread-safe and exactly-once per chunk: the first reader of a chunk
    becomes the decode owner (the GIL-released native chunk call runs
    OUTSIDE the registry lock); concurrent cold readers of the same
    chunk wait on the owner's event instead of decoding again — the
    multi-thread first-read soak in tests/test_lazy_decode.py pins one
    `decode_chunk_calls_total` increment per chunk."""

    def __init__(self, rr, n_pods: int | None = None, sealed: bool = False):
        self.rr = rr
        self.n = rr.cw.n_pods if n_pods is None else n_pods
        cc = getattr(rr, "_compact", None)
        self.chunk = cc.chunk if cc is not None else _FALLBACK_CHUNK
        self._mu = threading.Lock()
        self._chunks: dict[int, list] = {}
        self._inflight: dict[int, threading.Event] = {}
        # streaming waves seal at replay drain: a reader arriving while
        # the device is still filling rr blocks here instead of decoding
        # a half-delivered chunk (width-tier reruns rewrite early data)
        self._ready = threading.Event()
        if sealed:
            self._ready.set()

    def seal(self) -> None:
        """The wave's replay has fully drained; reads may decode."""
        self._ready.set()

    @property
    def sealed(self) -> bool:
        return self._ready.is_set()

    @property
    def materialized_pods(self) -> int:
        with self._mu:
            return sum(len(c) for c in self._chunks.values())

    def get(self, i: int) -> dict[str, str]:
        """Pod i's 13 annotation blobs, decoding its chunk on first
        read.  Returned dicts are shared and must not be mutated."""
        ci = i // self.chunk
        return self._chunk(ci)[i - ci * self.chunk]

    def _chunk(self, ci: int) -> list:
        with self._mu:
            got = self._chunks.get(ci)
        if got is not None:
            TRACER.inc("decode_on_demand_total", result="hit")
            return got
        t0 = time.perf_counter()
        self._ready.wait()
        while True:
            with self._mu:
                got = self._chunks.get(ci)
                if got is not None:
                    break
                ev = self._inflight.get(ci)
                owner = ev is None
                if owner:
                    ev = self._inflight[ci] = threading.Event()
            if not owner:
                ev.wait()
                # a failed decode hands its error to the readers that
                # were already waiting on it (the attribute rides the
                # event); a FRESH read retries the decode instead — a
                # transient failure (allocation pressure, an injected
                # chaos fault, an interrupt mid-read) must heal on
                # re-read, never poison the chunk (docs/fault-injection.md;
                # decode_failures_total counts the failure)
                err = getattr(ev, "error", None)
                if err is not None:
                    raise err
                continue  # re-check: the owner memoized the chunk
            lo = ci * self.chunk
            hi = min(lo + self.chunk, self.n)
            sink: list = [None] * (hi - lo)
            try:
                from .decode import decode_chunk_into

                with TRACER.span("decode_lazy", lo=lo, hi=hi):
                    decode_chunk_into(self.rr, lo, hi, sink, base=lo)
            except BaseException as e:  # noqa: BLE001 — replayed to waiters
                ev.error = e
                with self._mu:
                    del self._inflight[ci]
                ev.set()
                raise
            with self._mu:
                self._chunks[ci] = sink
                del self._inflight[ci]
            ev.set()
            got = sink
            break
        # waiters on an in-flight decode are cold reads too: their
        # latency is the wait, not a second decode
        TRACER.inc("decode_on_demand_total", result="miss")
        TRACER.observe("lazy_decode_cold_read_seconds",
                       time.perf_counter() - t0)
        return got
