"""Store reflector: write scheduling results back onto Pod annotations.

Capability parity with the reference reflector (reference:
simulator/scheduler/storereflector/storereflector.go):

  * merges the stored result maps of all registered result stores into the
    pod's annotations (:113-129);
  * appends the merged result set to the `result-history` annotation,
    dropping entries from the OLDEST side until the encoded array fits the
    256KiB apiserver annotation limit (:163-190);
  * updates the pod with re-fetch + conflict retry under exponential
    backoff (100ms x3, 6 steps — :136-151, util/retry.go:10-27), deletes
    the store entry only after a successful write (:156-159).

The reference triggers this from a Pod-informer Update handler; here the
scheduling engine calls reflect() after binding (same effect, no informer
round-trip needed in-process), and an optional watch-driven mode mirrors
the informer wiring for externally-bound pods.
"""

from __future__ import annotations

import json

from . import annotations as ann
from ..cluster.store import Conflict, NotFound, ObjectStore
from ..utils.faults import fault_point
from ..utils.retry import retry_with_exponential_backoff
from ..utils.tracing import TRACER

RESULT_HISTORY_LIMIT = ann.TOTAL_ANNOTATION_SIZE_LIMIT


def _encode_record(result_set: dict[str, str]) -> str:
    """marshal(result_set) — native escape pass when available (the
    values are whole annotation blobs; escaping them dominates the
    reflector's cost at cluster scale)."""
    from .native_decode import encode_string_map

    rec = encode_string_map(result_set)
    return rec if rec is not None else ann.marshal(result_set)


def _objects_only(raw: str) -> bool:
    """True when every element boundary in a compact JSON array is
    object-to-object: each "}," is followed by "{".  One scan, no parse;
    conservative — a "}," inside a string value false-positives and the
    caller just takes the slow parsing path instead."""
    i = raw.find("},")
    while i != -1:
        if i + 2 >= len(raw) or raw[i + 2] != "{":
            return False
        i = raw.find("},", i + 2)
    return True


def encode_history_record(result_set: dict[str, str]) -> str:
    """The encoded history record for result_set — precomputable OUTSIDE
    any store lock (it depends only on the result set, not the pod), so
    batched reflectors can pay the escape pass of ~250KB of blobs per
    pod off-lock.  Raises ValueError when the record alone cannot fit:
    JSON encoding never shrinks a string, so sum(len(k)+len(v))+syntax
    is a lower bound on the encoded record — when even that exceeds the
    limit (every pod at >=1k-node scale), raise before building and
    escaping hundreds of KB per pod."""
    lower_bound = 1 + sum(len(k) + len(v) + 6 for k, v in result_set.items())
    if lower_bound > RESULT_HISTORY_LIMIT:
        raise ValueError(
            "result record alone exceeds the annotation size limit"
        )
    return _encode_record(result_set)


def update_result_history(pod: dict, result_set: dict[str, str],
                          rec: str | None = None) -> None:
    """Append result_set to the result-history annotation, trimming oldest
    entries until the encoded JSON fits the 256KiB limit.

    Fast path: the existing history is this function's own output (a JSON
    array), so the new record is spliced in textually — no re-parse and
    no re-escape of the accumulated records.  The trim branch (only once
    the limit is hit) falls back to parse + drop-oldest.

    rec: the precomputed encode_history_record(result_set), when the
    caller already paid for it (the batched reflector encodes off-lock)."""
    annotations = pod.setdefault("metadata", {}).setdefault("annotations", {})
    raw = annotations.get(ann.RESULT_HISTORY, "[]")
    if rec is None:
        rec = encode_history_record(result_set)
    # textual-splice fast path: only for values shaped like this
    # function's own output (empty array, or array of objects) — anything
    # else falls through to the parsing path so corrupt histories raise
    # instead of being spliced into deeper corruption.  _objects_only
    # proves every element boundary is object-to-object without a full
    # parse (conservative: a legit value containing "}," that isn't a
    # boundary just falls to the slow path).  Residual trust: an object
    # element whose VALUES aren't strings (e.g. '[{"k":1,"m":"s"}]') can
    # keep the shell and splice where the reference's map[string]string
    # unmarshal would error — full validation would re-parse ~256 KiB
    # per pod, the cost this fast path exists to avoid.
    if raw == "[]" or (raw.startswith('[{"') and raw.endswith('"}]')
                       and _objects_only(raw)):
        encoded = ("[" + rec + "]" if raw == "[]"
                   else raw[:-1] + "," + rec + "]")
        if len(encoded) <= RESULT_HISTORY_LIMIT:
            annotations[ann.RESULT_HISTORY] = encoded
            return
    try:
        results = json.loads(raw)
    except json.JSONDecodeError as e:
        # the reference surfaces a broken existing history as an error
        # (updateResultHistory json.Unmarshal, storereflector.go:169-171)
        # rather than silently resetting it; reflect() treats this like
        # the oversized-record case (log-and-continue without history)
        raise ValueError(f"broken result-history annotation: {e}") from e
    if not isinstance(results, list):
        raise ValueError(
            "broken result-history annotation: not a JSON array")
    if any(not isinstance(r, dict) for r in results):
        # the reference unmarshals into []map[string]string and errors on
        # non-object elements ('[1,2]', '["a"]')
        raise ValueError(
            "broken result-history annotation: non-object element")
    if any(not isinstance(v, str) for r in results for v in r.values()):
        # ... and on non-string values ('[{"k":1}]')
        raise ValueError(
            "broken result-history annotation: non-string value")
    results.append(result_set)
    while results:
        encoded = ann.marshal(results)
        if len(encoded) <= RESULT_HISTORY_LIMIT:
            annotations[ann.RESULT_HISTORY] = encoded
            return
        results = results[1:]
    raise ValueError(
        "result history still exceeds annotation limit even after removing several histories"
    )


class _PendingRecord:
    """One deferred wave write-back for a pod: the uid the wave
    committed against (the reflect() recreation guard) and the ordered
    result parts (DeferredResult handles and/or eager dicts, in result
    -store registration order)."""

    __slots__ = ("uid", "parts")

    def __init__(self, uid: str | None, parts: list):
        self.uid = uid
        self.parts = parts

    def ready(self) -> bool:
        """True when materializing cannot block (every lazy part's wave
        is sealed).  A record queued by a still-streaming wave is NOT
        ready: a reader skips it — the bind event it trails is already
        annotation-less in eager mode too at that point — instead of
        stalling on the replay; it lands on the first read after the
        wave seals."""
        return all(p.ready() for p in self.parts if hasattr(p, "ready"))

    def result_set(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for part in self.parts:
            out.update(part.result_set() if hasattr(part, "result_set")
                       else part)
        return out


class LazyReflections:
    """Deferred reflector write-backs, drained by ObjectStore read hooks.

    reflect_batch() queues a _PendingRecord per pod instead of
    materializing blobs on the wave's critical path; the first read of
    the pod (GET / copying list / export / the HTTP watch stream)
    drains its queue — records apply IN ORDER, so a pod scheduled by
    several waves before anyone reads it gets exactly the eager path's
    annotation bytes and result-history sequence.  Exactly-once per
    pod under concurrent readers (in-flight event handshake); the
    decode — including the chunk's device->host materialization when
    the wave's results are device-resident (framework/replay.py, the
    `d2h_fetch` span) — and the store write run with NO registry lock
    held."""

    def __init__(self, store, stop=None):
        import threading

        self.store = store
        # owner's teardown event: interrupts the conflict-retry backoff
        # of a drain racing shutdown/eviction (utils/retry.py stop)
        self.stop = stop
        self._mu = threading.Lock()
        self._pending: dict[tuple[str, str], list[_PendingRecord]] = {}
        self._inflight: dict[tuple[str, str], object] = {}

    def add(self, namespace: str, name: str, uid: str | None,
            parts: list) -> None:
        key = (namespace or "default", name)
        with self._mu:
            self._pending.setdefault(key, []).append(
                _PendingRecord(uid, parts))

    def has(self, namespace: str, name: str) -> bool:
        with self._mu:
            return (namespace or "default", name) in self._pending

    def pending_count(self) -> int:
        with self._mu:
            return sum(len(v) for v in self._pending.values())

    # ------------------------------------------- ObjectStore hook surface

    def flush(self, resource: str | None, name: str | None = None,
              namespace: str | None = None) -> None:
        if resource not in (None, "pods"):
            return
        if name is not None:
            self._drain((namespace or "default", name))
            return
        self._drain_all()

    def discard(self, resource: str | None, name: str | None = None,
                namespace: str | None = None) -> None:
        if resource not in (None, "pods"):
            return
        with self._mu:
            if name is None:
                self._pending.clear()
            else:
                self._pending.pop((namespace or "default", name), None)

    # ---------------------------------------------------------- drain

    @staticmethod
    def _take_ready_locked(recs: list[_PendingRecord]) -> list[_PendingRecord]:
        """The longest READY prefix (order must hold: a later record may
        never land before an earlier one, so an unready record blocks
        everything after it — but never the reader)."""
        n = 0
        for rec in recs:
            if not rec.ready():
                break
            n += 1
        return recs[:n]

    def _drain(self, key: tuple[str, str]) -> None:
        import threading

        with self._mu:
            ev = self._inflight.get(key)
            if ev is not None:
                owner = False
            else:
                recs = self._pending.get(key)
                if not recs:
                    return
                ready = self._take_ready_locked(recs)
                if not ready:
                    return  # in-flight wave's timeline: skip, don't stall
                if len(ready) == len(recs):
                    del self._pending[key]
                else:
                    self._pending[key] = recs[len(ready):]
                recs = ready
                ev = self._inflight[key] = threading.Event()
                owner = True
        if not owner:
            # another reader is applying this pod's records: wait so our
            # caller's subsequent read observes the written annotations
            ev.wait()
            return
        try:
            self._apply(key, recs)
        except BaseException:
            with self._mu:
                # put the unapplied records back at the FRONT so order
                # is preserved for the next reader
                self._pending.setdefault(key, [])[:0] = recs
                del self._inflight[key]
            ev.set()
            raise
        with self._mu:
            del self._inflight[key]
        ev.set()

    def _drain_all(self) -> None:
        """Whole-resource flush (copying list / dump / export): ONE
        snapshot of the pending keys — records a concurrent wave adds
        mid-flush belong to that wave's timeline, not this read's — and
        one batched write through the store's apply_batch surface (a
        10k-pod drain costs one lock hold and one contiguous rv range,
        like the eager reflect_batch it replaces, instead of 10k
        conflict-retried updates)."""
        import threading

        if getattr(self.store, "apply_batch", None) is None:
            with self._mu:
                keys = list(self._pending)
            for key in keys:
                self._drain(key)
            return
        taken: list[tuple[tuple[str, str], list[_PendingRecord]]] = []
        events: dict[tuple[str, str], threading.Event] = {}
        busy: list[threading.Event] = []
        with self._mu:
            for key in list(self._pending):
                ev = self._inflight.get(key)
                if ev is not None:
                    busy.append(ev)
                    continue
                recs = self._pending[key]
                ready = self._take_ready_locked(recs)
                if not ready:
                    continue
                if len(ready) == len(recs):
                    del self._pending[key]
                else:
                    self._pending[key] = recs[len(ready):]
                ev = threading.Event()
                self._inflight[key] = ev
                events[key] = ev
                taken.append((key, ready))
        try:
            if taken:
                self._apply_batch(taken)
        except BaseException:
            with self._mu:
                for key, recs in taken:
                    self._pending.setdefault(key, [])[:0] = recs
                    del self._inflight[key]
            for ev in events.values():
                ev.set()
            raise
        with self._mu:
            for key in events:
                del self._inflight[key]
        for ev in events.values():
            ev.set()
        for ev in busy:
            # per-pod drains racing this flush: wait so the caller's
            # read observes their writes too
            ev.wait()

    def _apply_batch(self, taken) -> None:
        """Materialize + write many pods' deferred records through ONE
        apply_batch call.  The decode and the history-record encode (the
        escape pass over ~250KB of blobs per pod) run HERE, before the
        store lock — the mutate callbacks only merge and splice (the
        PR 2 off-lock rule, same as reflect_batch's prepare phase)."""
        prepared = []
        for key, recs in taken:
            sets = []
            for rec in recs:
                result_set = rec.result_set()
                hist_rec = None
                skip_history = False
                try:
                    hist_rec = encode_history_record(result_set)
                except ValueError as e:
                    skip_history = True
                    import sys

                    print(f"reflector: result-history not updated: {e}",
                          file=sys.stderr)
                sets.append((rec.uid, result_set, hist_rec, skip_history))
            prepared.append((key, sets))

        def mutation(key, sets):
            def mutate(pod: dict):
                meta = pod.get("metadata") or {}
                cur_uid = meta.get("uid")
                live = [s for s in sets
                        if not (s[0] and cur_uid not in (None, s[0]))]
                if not live:
                    return False
                annotations = dict(meta.get("annotations") or {})
                meta["annotations"] = annotations
                for _uid, result_set, hist_rec, skip_history in live:
                    annotations.update(result_set)
                    if skip_history:
                        continue
                    try:
                        update_result_history(pod, result_set, rec=hist_rec)
                    except ValueError as e:
                        import sys

                        print(f"reflector: result-history not updated: {e}",
                              file=sys.stderr)
                return True

            return mutate

        self.store.apply_batch("pods", [
            (key[1], key[0], mutation(key, sets))
            for key, sets in prepared
        ])

    def _apply(self, key: tuple[str, str], recs: list[_PendingRecord]) -> None:
        """reflect()'s per-pod semantics for a queue of deferred
        records: uid guard per record, annotation merge + history
        append in record order, ONE conflict-retried update."""
        namespace, name = key

        def attempt() -> tuple[bool, Exception | None]:
            try:
                fault_point("reflector.write_back")
            except Conflict:
                return False, None  # injected conflict: retry under backoff
            try:
                cur = self.store.get("pods", name, namespace,
                                     copy_object=False)
            except NotFound:
                return True, None
            cur_uid = (cur.get("metadata") or {}).get("uid")
            live = [r for r in recs
                    if not (r.uid and cur_uid not in (None, r.uid))]
            if not live:
                return True, None
            pod = dict(cur)
            meta = dict(cur.get("metadata") or {})
            annotations = dict(meta.get("annotations") or {})
            meta["annotations"] = annotations
            pod["metadata"] = meta
            for rec in live:
                result_set = rec.result_set()
                annotations.update(result_set)
                try:
                    update_result_history(pod, result_set)
                except ValueError as e:
                    import sys

                    print(f"reflector: result-history not updated: {e}",
                          file=sys.stderr)
            try:
                self.store.update("pods", pod, owned=True)
            except NotFound:
                return True, None
            except Conflict:
                return False, None  # re-fetch and retry
            return True, None

        retry_with_exponential_backoff(attempt, stop=self.stop)


def reflect_each(reflect_fn, items) -> None:
    """reflect_fn(ns, name, uid=uid) for EVERY (ns, name, uid) item even
    if an earlier one fails; the first error surfaces after the sweep —
    the per-pod fallback contract shared by reflect_batch and the
    engine's _ReflectBatcher (one place, so the wave-parity semantics
    cannot drift between them)."""
    first_err = None
    for ns, name, uid in items:
        try:
            reflect_fn(ns, name, uid=uid)
        except Exception as e:  # noqa: BLE001
            first_err = first_err or e
    if first_err is not None:
        raise first_err


class StoreReflector:
    def __init__(self, store: ObjectStore, sleep=None):
        import threading

        self.store = store
        self.result_stores: dict[str, object] = {}
        self._sleep = sleep  # injectable for tests
        # teardown interrupt: the write path's exponential backoff
        # sleeps up to ~36s; setting this (DIContainer.shutdown /
        # session eviction) wakes any in-flight backoff immediately
        # (utils/retry.py RetryAborted) instead of riding it out
        self.stop_event = threading.Event()
        self._watch_thread = None
        self._watch_queue = None
        self._lazy: LazyReflections | None = None

    def defer_supported(self) -> bool:
        """True when this reflector can defer wave write-backs: the
        store offers both the batched-commit surface and the read hooks
        that make deferred annotations transparent to readers."""
        return (getattr(self.store, "apply_batch", None) is not None
                and getattr(self.store, "add_read_hook", None) is not None)

    def lazy_pending(self) -> LazyReflections:
        """The deferred write-back registry, installed as a store read
        hook on first use (store/lazy.py module docs)."""
        if self._lazy is None:
            reg = LazyReflections(self.store, stop=self.stop_event)
            self.store.add_read_hook(reg)
            self._lazy = reg
        return self._lazy

    def add_result_store(self, result_store, key: str) -> None:
        """reference: storereflector.go AddResultStore."""
        self.result_stores[key] = result_store

    def register_result_saving_to_informer(self, stop_event) -> None:
        """The reference's informer wiring (ResisterResultSavingToInformer
        [sic], storereflector.go:56-81): a pod-update watcher that
        reflects stored results whenever a pod changes — the path an
        EXTERNAL scheduler's bind (through the HTTP API) takes, where no
        in-process engine calls reflect() after binding.  Do NOT enable it
        alongside an engine that reflects inline (the default simulator
        wiring): both paths appending the same record would duplicate it
        in result-history.  Idempotent; the watcher thread stops (and
        unsubscribes its queue) with stop_event."""
        import threading

        if self._watch_thread is not None:
            return
        _, rv = self.store.list("pods")
        q = self.store.watch("pods", since_rv=rv)
        self._watch_queue = q

        def pump():
            try:
                while not stop_event.is_set():
                    ev = q.get()
                    if ev is None:
                        return
                    _, event_type, obj = ev
                    if event_type == "DELETED":
                        # purge any unreflected results so a long-lived
                        # informer process doesn't accumulate entries for
                        # pods whose deletion-time updates were filtered
                        # (the reference leaks here; completing the
                        # cleanup matches our UID-guard precedent)
                        for rs in self.result_stores.values():
                            rs.delete_data(obj)
                        continue
                    if event_type != "MODIFIED":
                        continue
                    meta = obj.get("metadata") or {}
                    if meta.get("deletionTimestamp"):
                        # the reference's FilterFunc excludes pods being
                        # deleted (storereflector.go:61-68): no result
                        # write races a graceful deletion
                        continue
                    ns = meta.get("namespace") or "default"
                    name = meta.get("name", "")
                    # only fire when some store holds a result for the pod
                    # (the reference's handler re-GETs and no-ops
                    # otherwise; checking first avoids a write cycle per
                    # unrelated update).  has_result is the
                    # non-materializing probe — get_stored_result on a
                    # lazy entry would decode the pod's chunk per event
                    if any(rs.has_result(obj)
                           if hasattr(rs, "has_result")
                           else rs.get_stored_result(obj)
                           for rs in self.result_stores.values()):
                        try:
                            self.reflect(ns, name, uid=meta.get("uid"))
                        # kss-analyze: allow(swallowed-exception)
                        except Exception:
                            pass  # klog-and-continue, as the reference does
            finally:
                # stop_event exits must also unsubscribe, or the abandoned
                # unbounded queue keeps accumulating every pod event
                self.store.unwatch("pods", q)

        t = threading.Thread(target=pump, daemon=True,
                             name="reflector-informer")
        t.start()
        self._watch_thread = t

    def stop_informer(self) -> None:
        if self._watch_queue is not None:
            self.store.unwatch("pods", self._watch_queue)
            self._watch_queue.put(None)
            self._watch_queue = None
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None

    def reflect(self, namespace: str, name: str, uid: str | None = None) -> None:
        """Merge all result stores' data for the pod into its annotations
        (with history), conflict-retrying; delete store data on success.

        uid (when the caller knows it) guards against the pod having been
        deleted and recreated under the same name since scheduling — the
        reference aborts on UID mismatch (storereflector.go:107-109) so a
        fresh pod never inherits a stale result record."""
        if self._lazy is not None:
            # deferred records from earlier waves must land BEFORE this
            # cycle's result, or the pod's annotations and history would
            # reorder relative to the eager path
            self._lazy.flush("pods", name, namespace)

        last_pod: dict = {}

        def attempt() -> tuple[bool, Exception | None]:
            try:
                fault_point("reflector.write_back")
            except Conflict:
                return False, None  # injected conflict: retry under backoff
            try:
                cur = self.store.get("pods", name, namespace,
                                     copy_object=False)
            except NotFound:
                return True, None
            if uid and (cur.get("metadata") or {}).get("uid") not in (None, uid):
                # recreated pod: purge the stale record so the new pod's
                # own cycle starts clean (the reference merely errors out
                # and leaks the store entry, storereflector.go:107-109 —
                # deleting completes the guard's intent)
                stale = {"metadata": {"namespace": namespace, "name": name}}
                for rs in self.result_stores.values():
                    rs.delete_data(stale)
                return True, None
            result_set: dict[str, str] = {}
            for rs in self.result_stores.values():
                m = rs.get_stored_result(cur) or {}
                result_set.update(m)
            if not result_set:
                return True, None
            # copy-on-write along the touched path (metadata.annotations):
            # everything else stays shared with the stored object, which
            # is replaced — never mutated — by update()
            pod = dict(cur)
            meta = dict(cur.get("metadata") or {})
            annotations = dict(meta.get("annotations") or {})
            meta["annotations"] = annotations
            pod["metadata"] = meta
            annotations.update(result_set)
            try:
                update_result_history(pod, result_set)
            except ValueError as e:
                # log-and-continue, as the reference does
                # (storereflector.go:131-134 klog.Errorf then Update)
                import sys

                print(f"reflector: result-history not updated: {e}",
                      file=sys.stderr)
            try:
                # get() returned a private copy; transfer ownership (the
                # pod dict is only read below, which the contract allows)
                self.store.update("pods", pod, owned=True)
            except NotFound:
                return True, None
            except Conflict:
                return False, None  # re-fetch and retry
            last_pod.clear()
            last_pod.update(pod)
            return True, None

        kwargs = {"sleep": self._sleep} if self._sleep else {}
        retry_with_exponential_backoff(attempt, stop=self.stop_event,
                                       **kwargs)
        if last_pod:
            for rs in self.result_stores.values():
                rs.delete_data(last_pod)

    def reflect_batch(self, items) -> None:
        """reflect() for many pods through one ObjectStore.apply_batch
        call (conflict-free by construction, so no retry loop), then the
        result-store entries of the pods actually written are deleted —
        the engine's batched wave-commit surface.  items: iterable of
        (namespace, name, uid).  Stores without apply_batch (the remote
        HTTP client) fall back to per-pod reflect().

        Two phases so the expensive work stays OFF the store lock: the
        result-set merge and the history-record encode (the escape pass
        over ~250KB of blobs per pod — the dominant reflect cost at
        cluster scale) depend only on the result stores, so they run
        before apply_batch; the mutate callbacks then only splice and
        stamp under the lock, and a concurrent wave's binds never queue
        behind a batch of record encodes."""
        if getattr(self.store, "apply_batch", None) is None:
            reflect_each(self.reflect, items)
            return
        try:
            fault_point("reflector.write_back")
        except Exception:
            # a failed batch write-back degrades to the per-pod
            # conflict-retried path — same bytes, same record order,
            # just without the single-lock-hold batching
            TRACER.inc("wave_faults_total", seam="reflector.write_back",
                       action="batch_fallback")
            reflect_each(self.reflect, items)
            return
        defer_ok = getattr(self.store, "add_read_hook", None) is not None
        prepared: list[tuple] = []
        for ns, name, uid in items:
            key_pod = {"metadata": {"namespace": ns, "name": name}}
            # lazy entries defer whole: take the consumed snapshot into
            # the pending registry instead of decoding here — the wave's
            # critical path carries only tensor handles (store/lazy.py)
            parts: list = []
            any_lazy = False
            for rs in self.result_stores.values():
                d = None
                if defer_ok:
                    taker = getattr(rs, "take_deferred", None)
                    if taker is not None:
                        d = taker(ns, name)
                if d is not None:
                    parts.append(d)
                    any_lazy = True
                else:
                    m = rs.get_stored_result(key_pod) or {}
                    if m:
                        parts.append(m)
            if not parts:
                continue
            if any_lazy:
                self.lazy_pending().add(ns, name, uid, parts)
                continue
            if self._lazy is not None and self._lazy.has(ns, name):
                # eager result over a pod with older deferred records:
                # land those first so history order matches eager mode
                self._lazy.flush("pods", name, ns)
            result_set: dict[str, str] = {}
            for part in parts:
                result_set.update(part)
            if not result_set:
                continue
            rec = None
            skip_history = False
            try:
                rec = encode_history_record(result_set)
            except ValueError as e:
                # log-and-continue (reference storereflector.go:131-134)
                # HERE, off-lock — at >=1k-node scale every record
                # overflows and a per-pod stderr write under the store
                # lock would serialize the whole batch against binds
                skip_history = True
                import sys

                print(f"reflector: result-history not updated: {e}",
                      file=sys.stderr)
            prepared.append((ns, name, uid, result_set, rec, skip_history))
        if not prepared:
            return
        written: list[dict] = []
        self.store.apply_batch("pods", [
            (name, ns, self._reflect_mutation(ns, name, uid, result_set,
                                              rec, skip_history, written))
            for ns, name, uid, result_set, rec, skip_history in prepared
        ])
        for pod in written:
            for rs in self.result_stores.values():
                rs.delete_data(pod)

    def _reflect_mutation(self, namespace: str, name: str, uid: str | None,
                          result_set: dict[str, str], rec: str | None,
                          skip_history: bool, written: list):
        """apply_batch mutate callback with reflect()'s per-pod logic:
        UID guard (purge-and-skip on a recreated pod), annotation merge,
        history append (log-and-continue on ValueError) using the
        pre-encoded record; skip_history marks an oversize record the
        prepare phase already logged."""

        def mutate(pod: dict):
            meta = pod.get("metadata") or {}
            if uid and meta.get("uid") not in (None, uid):
                stale = {"metadata": {"namespace": namespace, "name": name}}
                for rs in self.result_stores.values():
                    rs.delete_data(stale)
                return False
            # metadata is already copy-on-write fresh (the apply_batch
            # contract); the annotations dict below it is still shared
            annotations = dict(meta.get("annotations") or {})
            meta["annotations"] = annotations
            annotations.update(result_set)
            if not skip_history:
                try:
                    update_result_history(pod, result_set, rec=rec)
                except ValueError as e:
                    import sys

                    print(f"reflector: result-history not updated: {e}",
                          file=sys.stderr)
            written.append(pod)
            return True

        return mutate
