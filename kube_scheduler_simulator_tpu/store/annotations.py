"""Result annotation keys — exact parity with the reference.

reference: simulator/scheduler/plugin/annotation/annotation.go:3-30 (13
plugin keys), simulator/scheduler/extender/annotation/annotation.go:3-12
(4 extender keys), simulator/scheduler/storereflector/annotation.go:4
(result history).
"""

PREFIX = "kube-scheduler-simulator.sigs.k8s.io/"

PRE_FILTER_STATUS_RESULT = PREFIX + "prefilter-result-status"
PRE_FILTER_RESULT = PREFIX + "prefilter-result"
FILTER_RESULT = PREFIX + "filter-result"
POST_FILTER_RESULT = PREFIX + "postfilter-result"
PRE_SCORE_RESULT = PREFIX + "prescore-result"
SCORE_RESULT = PREFIX + "score-result"
FINAL_SCORE_RESULT = PREFIX + "finalscore-result"
RESERVE_RESULT = PREFIX + "reserve-result"
PERMIT_STATUS_RESULT = PREFIX + "permit-result"
PERMIT_TIMEOUT_RESULT = PREFIX + "permit-result-timeout"
PRE_BIND_RESULT = PREFIX + "prebind-result"
BIND_RESULT = PREFIX + "bind-result"
SELECTED_NODE = PREFIX + "selected-node"

EXTENDER_FILTER_RESULT = PREFIX + "extender-filter-result"
EXTENDER_PRIORITIZE_RESULT = PREFIX + "extender-prioritize-result"
EXTENDER_PREEMPT_RESULT = PREFIX + "extender-preempt-result"
EXTENDER_BIND_RESULT = PREFIX + "extender-bind-result"

RESULT_HISTORY = PREFIX + "result-history"

# messages, reference: simulator/scheduler/plugin/resultstore/store.go:26-35
PASSED_FILTER_MESSAGE = "passed"
SUCCESS_MESSAGE = "success"
WAIT_MESSAGE = "wait"
POST_FILTER_NOMINATED_MESSAGE = "preemption victim"

# the apiserver's total annotation size limit the reflector trims history
# to (reference: storereflector.go:177-190, validation.TotalAnnotationSizeLimitB)
TOTAL_ANNOTATION_SIZE_LIMIT = 256 * 1024

ALL_PLUGIN_KEYS = [
    PRE_FILTER_STATUS_RESULT, PRE_FILTER_RESULT, FILTER_RESULT,
    POST_FILTER_RESULT, PRE_SCORE_RESULT, SCORE_RESULT, FINAL_SCORE_RESULT,
    RESERVE_RESULT, PERMIT_STATUS_RESULT, PERMIT_TIMEOUT_RESULT,
    PRE_BIND_RESULT, BIND_RESULT, SELECTED_NODE,
]


def marshal(obj) -> str:
    """Go encoding/json-compatible: compact, map keys sorted, HTML-escaped.

    Go escapes < > & to \\u003c \\u003e \\u0026 by default; scheduler
    messages and k8s names never contain them, but match anyway.
    """
    import json

    s = json.dumps(obj, sort_keys=True, separators=(",", ":"), ensure_ascii=False)
    return s.replace("<", "\\u003c").replace(">", "\\u003e").replace("&", "\\u0026")
