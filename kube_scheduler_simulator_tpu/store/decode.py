"""Device arrays -> per-pod result annotations.

Reconstructs exactly what the reference's result store would serialize for
each pod (reference: simulator/scheduler/plugin/resultstore/store.go:133-198
GetStoredResult -> 13 JSON blobs), from the ReplayResult tensors:

  * stop-at-first-fail truncation of the filter map (the framework stops
    running Filter plugins for a node at the first failure);
  * scoring recorded only when >1 node was feasible (upstream schedulePod
    early-returns on a single feasible node, skipping PreScore/Score);
  * score map covers only feasible nodes (only they are scored);
  * PreFilter/PreScore Skip recorded as "" (Skip status has an empty
    message; wrappedplugin.go:507-516 records status.Message());
  * finalscore = normalized score x plugin weight
    (resultstore/store.go:488-507).
"""

from __future__ import annotations

import os

import numpy as np

from . import annotations as ann
from ..utils.faults import fault_point
from ..utils.platform import effective_cpu_count
from ..utils.tracing import TRACER
from ..framework.replay import ReplayResult
from ..plugins import (
    affinity, interpod, noderesources, nodevolumelimits, ports, taints,
    topologyspread, volumebinding, volumerestrictions, volumezone,
)
from ..plugins.registry import PLUGIN_REGISTRY


def _native_ctx(cw):
    """Per-workload native-codec context; None disables the fast path
    (or set KSS_TPU_DISABLE_NATIVE=1 to force the Python encoder)."""
    if os.environ.get("KSS_TPU_DISABLE_NATIVE") == "1":
        return None
    if "_native_ctx" not in cw.host:
        from . import native_decode

        try:
            cw.host["_native_ctx"] = native_decode.build_context(cw)
        except Exception:
            cw.host["_native_ctx"] = None
    return cw.host["_native_ctx"]

_DECODERS = {
    "NodeResourcesFit": lambda code, node, aux: noderesources.decode_fit_filter(code, aux["schema"]),
    "NodeAffinity": affinity.decode_filter,
    "TaintToleration": taints.decode_taint_filter,
    "NodeUnschedulable": lambda code, node, aux: taints.ERR_UNSCHEDULABLE,
    "NodeName": lambda code, node, aux: taints.ERR_NODE_NAME,
    "NodePorts": lambda code, node, aux: ports.ERR_NODE_PORTS,
    "PodTopologySpread": topologyspread.decode_filter,
    "InterPodAffinity": interpod.decode_filter,
    "VolumeRestrictions": lambda code, node, aux: volumerestrictions.ERR_DISK_CONFLICT,
    "NodeVolumeLimits": lambda code, node, aux: nodevolumelimits.ERR_MAX_VOLUME_COUNT,
    "VolumeBinding": lambda code, node, aux: volumebinding.decode_filter(code, node, aux),
    "VolumeZone": lambda code, node, aux: volumezone.ERR_VOLUME_ZONE_CONFLICT,
}


def prefilter_reject_message(cw, i: int, dynamic_code: int) -> tuple[str, str] | None:
    """(plugin name, message) of the PreFilter reject that aborted pod i's
    cycle, or None.  Resolution follows upstream RunPreFilterPlugins: the
    first rejecting plugin in config order wins; within VolumeRestrictions
    the static (PVC-lister) reject precedes the dynamic ReadWriteOncePod
    conflict."""
    static = cw.host.get("prefilter_reject", {})
    if not static and not dynamic_code:
        return None
    for name in cw.config.prefilters():
        msgs = static.get(name)
        if msgs is not None and msgs[i] is not None:
            return name, msgs[i]
        if name == "VolumeRestrictions" and (dynamic_code & 1):
            return name, volumerestrictions.ERR_RWOP_CONFLICT
    return None


def decode_filter_message(name: str, code: int, node_idx: int, host_aux) -> str:
    dec = _DECODERS.get(name)
    if dec is None:  # custom plugin: interned message table
        return host_aux["custom_msgs"][name][code - 1]
    return dec(code, node_idx, host_aux)


def decode_pod_result(rr: ReplayResult, i: int, feasible_override=None,
                      host_index: int | None = None) -> dict[str, str]:
    """The 13 plugin annotations for pod i, values JSON-encoded as Go would.

    feasible_override: [N] bool — the extender path narrows feasibility
    after the plugin filters (upstream scores only nodes that survive the
    extender Filter round-trip too); overrides the feasibility derived
    from the plugin filter codes for the score maps.
    host_index: index into the CompiledWorkload's per-pod host tables
    (skip flags, static prefilter rejects) when it differs from `i` — the
    extender path builds single-row ReplayResults (i=0) against the full
    workload's cw."""
    cw = rr.cw
    hi = i if host_index is None else host_index
    cfg = cw.config
    names = cw.node_table.names
    filter_names = cfg.filters()
    score_names = cfg.scorers()
    fskip = cw.host["filter_skip"]
    sskip = cw.host["score_skip"]

    # --- prefilter reject: the cycle aborted before Filter --------------
    reject = prefilter_reject_message(cw, hi, int(rr.prefilter_reject[i]))
    if reject is not None:
        rej_name, rej_msg = reject
        pf: dict[str, str] = {}
        for name in cfg.prefilters():
            if name == rej_name:
                pf[name] = rej_msg
                break
            pf[name] = "" if fskip[name][hi] else ann.SUCCESS_MESSAGE
        empty = _marshal_small({})
        return {
            ann.PRE_FILTER_STATUS_RESULT: _marshal_small(pf),
            ann.PRE_FILTER_RESULT: empty,
            ann.FILTER_RESULT: empty,
            ann.POST_FILTER_RESULT: empty,
            ann.PRE_SCORE_RESULT: empty,
            ann.SCORE_RESULT: empty,
            ann.FINAL_SCORE_RESULT: empty,
            ann.RESERVE_RESULT: empty,
            ann.PERMIT_STATUS_RESULT: empty,
            ann.PERMIT_TIMEOUT_RESULT: empty,
            ann.PRE_BIND_RESULT: empty,
            ann.BIND_RESULT: empty,
            ann.SELECTED_NODE: "",
        }

    # --- prefilter ------------------------------------------------------
    prefilter_status = {}
    for name in cfg.prefilters():
        prefilter_status[name] = "" if fskip[name][hi] else ann.SUCCESS_MESSAGE

    native_ctx = _native_ctx(cw)

    # --- fused native path (compact replay layout only) -----------------
    if (native_ctx is not None and getattr(rr, "_compact", None) is not None
            and feasible_override is None):
        from . import native_decode

        feasible_count = int(rr.feasible_count[i])
        filter_json, score_json, final_json = native_decode.decode_pod_fused(
            native_ctx, rr, i, hi, feasible_count > 1)
        prescore = {}
        if feasible_count > 1:
            for name in cfg.prescorers():
                prescore[name] = "" if sskip[name][hi] else ann.SUCCESS_MESSAGE
        return _assemble(cw, cfg, names, rr, i, prefilter_status, prescore,
                         filter_json, score_json, final_json)

    # --- filter (stop at first fail per node) ---------------------------
    active = [
        (f, name) for f, name in enumerate(filter_names) if not fskip[name][hi]
    ]
    codes = rr.codes_of(i)  # [F, N]

    filter_json: str | None = None
    if native_ctx is not None:
        from . import native_decode

        active_mask = np.asarray([not fskip[name][hi] for name in filter_names], np.uint8)
        filter_json = native_decode.encode_filter(native_ctx, codes, active_mask)
    else:
        filter_map: dict[str, dict[str, str]] = {}
        for n, node in enumerate(names):
            entry = {}
            for f, name in active:
                c = int(codes[f, n])
                if c == 0:
                    entry[name] = ann.PASSED_FILTER_MESSAGE
                else:
                    entry[name] = decode_filter_message(name, c, n, cw.host)
                    break
            if entry:
                filter_map[node] = entry

    # --- score (only when >1 feasible node) -----------------------------
    feasible_count = int(rr.feasible_count[i])
    prescore: dict[str, str] = {}
    score_map: dict[str, dict[str, str]] = {}
    final_map: dict[str, dict[str, str]] = {}
    score_json: str | None = None
    final_json: str | None = None
    if feasible_count > 1:
        for name in cfg.prescorers():
            prescore[name] = "" if sskip[name][hi] else ann.SUCCESS_MESSAGE
        feasible = rr.feasible_of(i)
        if feasible is None:
            feasible = (codes[[f for f, _ in active], :] == 0).all(axis=0) if active else None
        if feasible_override is not None:
            feasible = feasible_override
        raw = rr.raw_of(i)
        fin = rr.final_of(i)
        if native_ctx is not None:
            from . import native_decode

            sskip_mask = np.asarray([bool(sskip[name][hi]) for name in score_names], np.uint8)
            feas = (
                np.ones(len(names), np.uint8) if feasible is None
                else np.asarray(feasible, np.uint8)
            )
            score_json = native_decode.encode_scores(native_ctx, raw, sskip_mask, feas)
            final_json = native_decode.encode_scores(native_ctx, fin, sskip_mask, feas)
        else:
            for n, node in enumerate(names):
                if feasible is not None and not feasible[n]:
                    continue
                se, fe = {}, {}
                for s, name in enumerate(score_names):
                    if sskip[name][hi]:
                        continue
                    se[name] = str(int(raw[s, n]))
                    fe[name] = str(int(fin[s, n]))
                if se:
                    score_map[node] = se
                    final_map[node] = fe

    return _assemble(
        cw, cfg, names, rr, i, prefilter_status, prescore,
        filter_json if filter_json is not None else ann.marshal(filter_map),
        score_json if score_json is not None else ann.marshal(score_map),
        final_json if final_json is not None else ann.marshal(final_map))


_MARSHAL_CACHE: dict = {}


def _marshal_small(d: dict) -> str:
    """marshal() memoized for the tiny per-pod status maps — they repeat
    across pods (a handful of distinct skip patterns per workload), and
    the per-pod json.dumps churn was ~15% of an engine wave."""
    key = tuple(sorted(d.items()))
    s = _MARSHAL_CACHE.get(key)
    if s is None:
        if len(_MARSHAL_CACHE) > 4096:
            _MARSHAL_CACHE.clear()
        s = _MARSHAL_CACHE.setdefault(key, ann.marshal(d))
    return s


def _assemble(cw, cfg, names, rr, i: int, prefilter_status: dict,
              prescore: dict, filter_json: str, score_json: str | None,
              final_json: str | None) -> dict[str, str]:
    """Bind-phase maps + the 13-key annotation dict (both decode paths)."""
    sel = int(rr.selected[i])
    scheduled = sel >= 0
    bind = {"DefaultBinder": ann.SUCCESS_MESSAGE} if scheduled else {}
    # VolumeBinding is the only default plugin implementing Reserve and
    # PreBind (assume/bind the chosen PVs); the reference shim records
    # "success" for each on the happy path
    # (reference: simulator/scheduler/plugin/wrappedplugin.go:622-651, :653-700)
    reserve: dict[str, str] = {}
    prebind: dict[str, str] = {}
    if scheduled and "VolumeBinding" in cfg.enabled and not cfg.is_custom("VolumeBinding"):
        reserve["VolumeBinding"] = ann.SUCCESS_MESSAGE
        prebind["VolumeBinding"] = ann.SUCCESS_MESSAGE

    empty = _marshal_small({})
    return {
        ann.PRE_FILTER_STATUS_RESULT: _marshal_small(prefilter_status),
        ann.PRE_FILTER_RESULT: empty,
        ann.FILTER_RESULT: filter_json,
        ann.POST_FILTER_RESULT: empty,
        ann.PRE_SCORE_RESULT: _marshal_small(prescore),
        ann.SCORE_RESULT: score_json if score_json is not None else empty,
        ann.FINAL_SCORE_RESULT: final_json if final_json is not None else empty,
        ann.RESERVE_RESULT: _marshal_small(reserve),
        ann.PERMIT_STATUS_RESULT: empty,
        ann.PERMIT_TIMEOUT_RESULT: empty,
        ann.PRE_BIND_RESULT: _marshal_small(prebind),
        ann.BIND_RESULT: _marshal_small(bind),
        ann.SELECTED_NODE: names[sel] if scheduled else "",
    }


def decode_all(rr: ReplayResult) -> list[dict[str, str]]:
    return [decode_pod_result(rr, i) for i in range(rr.cw.n_pods)]


_DECODE_POOL = None


def _decode_pool():
    global _DECODE_POOL
    if _DECODE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _DECODE_POOL = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="decode")
    return _DECODE_POOL


def _chunk_skip_mask(rr, lo: int, hi: int):
    """[hi-lo] uint8 marking prefilter-rejected pods (the Python
    early-out owns them — their cycle aborted before Filter, so there
    are no blobs to decode), or None when the range has none.

    Mirrors prefilter_reject_message's not-None condition exactly: a
    static (PVC-lister) reject for the pod, or the dynamic
    ReadWriteOncePod conflict bit with VolumeRestrictions enabled.  The
    static part is a pure function of the workload, so it's vectorized
    once per cw — no per-pod Python on the chunk-decode hot path."""
    cw = rr.cw
    static = cw.host.get("prefilter_reject", {})
    dyn = np.asarray(rr.prefilter_reject[lo:hi])
    if not static and not dyn.any():
        return None
    mask = cw.host.get("_static_reject_any")
    if mask is None:
        mask = np.zeros(cw.n_pods, bool)
        for msgs in static.values():
            mask |= np.asarray([m is not None for m in msgs], bool)
        cw.host["_static_reject_any"] = mask
    skip = mask[lo:hi].copy()
    if "VolumeRestrictions" in cw.config.prefilters():
        skip |= (dyn & 1).astype(bool)
    if not skip.any():
        return None
    return np.ascontiguousarray(skip, np.uint8)


def _assemble_chunk(rr, lo: int, hi: int, triples, out: list,
                    base: int) -> None:
    """Per-pod tail of the chunk decode: blob strs -> the 13-key dicts."""
    cw = rr.cw
    cfg = cw.config
    names = cw.node_table.names
    fskip = cw.host["filter_skip"]
    sskip = cw.host["score_skip"]
    prefilters = cfg.prefilters()
    prescorers = cfg.prescorers()
    feasible_count = rr.feasible_count
    for i in range(lo, hi):
        t = triples[i - lo]
        if t is None:  # prefilter reject: the early-out path owns it
            out[i - base] = decode_pod_result(rr, i)
            continue
        filter_json, score_json, final_json = t
        prefilter_status = {
            name: "" if fskip[name][i] else ann.SUCCESS_MESSAGE
            for name in prefilters
        }
        prescore = {}
        if int(feasible_count[i]) > 1:
            for name in prescorers:
                prescore[name] = "" if sskip[name][i] else ann.SUCCESS_MESSAGE
        out[i - base] = _assemble(cw, cfg, names, rr, i, prefilter_status,
                                  prescore, filter_json, score_json,
                                  final_json)


def _decode_chunk_native(rr, lo: int, hi: int, out: list, base: int) -> bool:
    """Pods lo..hi (a range within ONE compact chunk) through the
    chunk-granular native call: one GIL-released ctx_decode_chunk runs
    the C worker pool over the whole range and hands back arena blob
    addresses; Python keeps only the prefilter-reject early-out and the
    13-key _assemble.  False -> caller falls back (no native ctx)."""
    ctx = _native_ctx(rr.cw)
    if ctx is None:
        return False
    from . import native_decode

    with TRACER.span("decode_chunk", lo=lo, hi=hi, path="native_chunk"):
        triples, thread_s = native_decode.decode_chunk_fused(
            ctx, rr, lo, hi, skip=_chunk_skip_mask(rr, lo, hi))
        TRACER.count("decode_chunk_calls_total")
        TRACER.count("decode_native_thread_seconds", round(thread_s, 6))
        TRACER.inc("decode_path_total", hi - lo, path="native_chunk")
        _assemble_chunk(rr, lo, hi, triples, out, base)
    return True


def decode_chunk_into(rr, lo: int, hi: int, out: list, base: int = 0) -> None:
    """Decode pods lo..hi of one replay chunk into out[lo-base:hi-base] —
    the replay(on_chunk=...) streaming consumer: runs on the dispatch
    thread while the device executes later chunks.  Idempotent per index
    (a width-tier rerun re-delivers chunks).  base: offset for callers
    passing a chunk-local sink (out[i-base]) instead of a queue-length
    list.

    Decoder ladder (docs/wave-pipeline.md): chunk-granular native call
    (one GIL-released C call per compact chunk, C-side worker pool) ->
    per-pod fused native decode on the Python thread pool -> pure-Python
    encoder (KSS_TPU_DISABLE_NATIVE=1, or no toolchain).

    A failed decode re-raises to its caller but is VISIBLE now
    (decode_failures_total{path=...}) and never poisons the chunk: the
    lazy read path clears for retry (store/lazy.py), so a transient
    fault heals on the next read — tests/test_faults.py pins this."""
    try:
        _decode_chunk_into(rr, lo, hi, out, base)
    except Exception:
        TRACER.inc("decode_failures_total", path=_decode_path_label(rr))
        raise


def _decode_path_label(rr) -> str:
    """Best-effort decode-path label for the failure tap (the ladder
    the failed call would have taken)."""
    try:
        if _native_ctx(rr.cw) is None:
            return "python"
        return ("native_chunk" if getattr(rr, "_compact", None) is not None
                else "native_pod")
    except Exception:
        return "unknown"


def _decode_chunk_into(rr, lo: int, hi: int, out: list, base: int) -> None:
    fault_point("decode.chunk")
    cc = getattr(rr, "_compact", None)
    if cc is not None:
        # chunk-granular native decode; ranges spanning several compact
        # chunks (full-queue callers) split on chunk boundaries
        s0, routed = lo, True
        while s0 < hi:
            s1 = min(hi, (s0 // cc.chunk + 1) * cc.chunk)
            if not _decode_chunk_native(rr, s0, s1, out, base):
                routed = False
                break
            s0 = s1
        if routed:
            return
        lo = s0  # keep anything the native path already decoded
    fallback_path = ("native_pod" if _native_ctx(rr.cw) is not None
                     else "python")
    if hi - lo < 16 or effective_cpu_count() < 2:
        # single-core hosts: the pool's dispatch + recon-lock traffic
        # costs more than the GIL-released C calls can win back
        TRACER.inc("decode_path_total", hi - lo, path=fallback_path)
        for i in range(lo, hi):
            out[i - base] = decode_pod_result(rr, i)
        return
    with TRACER.span("decode_chunk", lo=lo, hi=hi, path=fallback_path):
        TRACER.inc("decode_path_total", hi - lo, path=fallback_path)
        if cc is not None and _native_ctx(rr.cw) is None:
            # pure-Python path reads codes_of/raw_of/final_of: reconstruct
            # the chunk once here so pool workers share it.  The fused
            # native path reads the compact arrays directly — warming recon
            # for it would re-create exactly the [C,F,N]/[C,S,N]
            # materialization it avoids.  (full-array results — the
            # speculative path — need no recon)
            rr._chunk_recon(lo // cc.chunk, scores=True)
        for i, a in zip(range(lo, hi),
                        _decode_pool().map(lambda i: decode_pod_result(rr, i),
                                           range(lo, hi))):
            out[i - base] = a


def decode_release_batches(rr, lo: int, hi: int, on_pod=None,
                           batch: int = 64) -> None:
    """Decode pods lo..hi in small compact-chunk-aligned batches,
    releasing each batch's annotations after on_pod(i, ann) — the
    reflector-style consumer (holds nothing, BASELINE.md): holding a
    whole replay chunk's strings before releasing pays ~1.3 GB of
    first-touch page faults at the 5k-node shape, a harness transient
    rather than decoder cost.  Batches never straddle a compact chunk.

    On the chunk-granular native path the batches PIPELINE: batch k+1's
    GIL-released C decode runs on a pool thread while this thread builds
    batch k's strs and fires on_pod — on a 2-core host that hides most
    of the C wall time behind the (GIL-bound) str assembly.  Pod order
    of on_pod calls is preserved."""
    cc = getattr(rr, "_compact", None)
    ranges: list[tuple[int, int]] = []
    s0 = lo
    while s0 < hi:
        s1 = min(s0 + batch, hi)
        if cc is not None:
            s1 = min(s1, (s0 // cc.chunk + 1) * cc.chunk)
        ranges.append((s0, s1))
        s0 = s1

    ctx = _native_ctx(rr.cw) if cc is not None else None
    if ctx is not None:
        from . import native_decode

        pool = _decode_pool()

        def start(r):
            return pool.submit(
                native_decode.decode_chunk_start, ctx, rr, r[0], r[1],
                _chunk_skip_mask(rr, *r))

        fut = start(ranges[0]) if ranges else None
        try:
            for k, (b0, b1) in enumerate(ranges):
                fault_point("decode.chunk")
                handle = fut.result()
                fut = start(ranges[k + 1]) if k + 1 < len(ranges) else None
                triples = native_decode.decode_chunk_take(handle)
                TRACER.count("decode_chunk_calls_total")
                TRACER.count("decode_native_thread_seconds",
                             round(handle.thread_seconds, 6))
                TRACER.inc("decode_path_total", b1 - b0, path="native_chunk")
                sink: list = [None] * (b1 - b0)
                _assemble_chunk(rr, b0, b1, triples, sink, b0)
                if on_pod is not None:
                    for j, a in enumerate(sink):
                        if a is not None:
                            on_pod(b0 + j, a)
        except BaseException as e:
            if isinstance(e, Exception):
                TRACER.inc("decode_failures_total", path="native_chunk")
            if fut is not None:  # don't leak the in-flight arena
                try:
                    fut.result().discard()
                # best-effort arena release on an already-raising path
                # (the original error re-raises below)
                # kss-analyze: allow(swallowed-exception)
                except Exception:
                    pass
            raise
        return

    for b0, b1 in ranges:
        sink = [None] * (b1 - b0)
        decode_chunk_into(rr, b0, b1, sink, base=b0)
        if on_pod is not None:
            for j, a in enumerate(sink):
                if a is not None:
                    on_pod(b0 + j, a)


def decode_all_parallel(rr: ReplayResult,
                        n: int | None = None) -> list[dict[str, str]]:
    """Decode pods 0..n across a thread pool, chunk by chunk.

    The native codec runs outside the GIL — one ctx_decode_chunk call per
    compact chunk drives the C-side worker pool (decode_chunk_into's
    ladder), so the JSON encoding — the dominant cost at cluster scale —
    parallelizes without per-pod Python dispatch.  Falls back to the
    serial loop when the ReplayResult holds full arrays (host path)."""
    if n is None:
        n = rr.cw.n_pods
    cc = getattr(rr, "_compact", None)
    if cc is None:
        return [decode_pod_result(rr, i) for i in range(n)]
    out: list = [None] * n
    for lo in range(0, n, cc.chunk):
        decode_chunk_into(rr, lo, min(lo + cc.chunk, n), out)
    return out
