"""Native fast path for the annotation decoder.

Builds per-workload context (name arrays, sorted orders, message LUTs) for
native/annotation_codec.cpp and encodes the three heavy blobs
(filter-result, score-result, finalscore-result) in C++.  Used by
store/decode.py when the native codec is available; output is
byte-identical to the Python path (asserted by tests/test_native_codec.py).
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..native import (
    get_lib, peek_string, peek_string_ascii, take_sized_string,
    take_sized_string_ascii,
)
from ..plugins import (
    affinity, interpod, nodevolumelimits, ports, taints, topologyspread,
    volumebinding, volumerestrictions, volumezone,
)
from ..plugins.noderesources import decode_fit_filter

_MAX_FIT_LUT_BITS = 16


def _c_str_array(strings: list[bytes]):
    arr = (ctypes.c_char_p * len(strings))(*strings)
    return arr


def build_context(cw):
    """-> context dict or None when a plugin's messages can't be LUT'd."""
    lib = get_lib()
    if lib is None:
        return None
    table = cw.node_table
    n = table.n
    filter_names = cw.config.filters()
    score_names = cw.config.scorers()

    luts: list[list[bytes]] = []
    per_node: list[int] = []
    for name in filter_names:
        if name == "NodeResourcesFit":
            bits = cw.schema.n + 1
            if bits > _MAX_FIT_LUT_BITS:
                return None
            lut = [
                decode_fit_filter(code, cw.schema).encode()
                for code in range(1, (1 << bits))
            ]
            per_node.append(0)
        elif name == "NodeAffinity":
            lut = [affinity.ERR_REASON.encode()]
            per_node.append(0)
        elif name == "NodeUnschedulable":
            lut = [taints.ERR_UNSCHEDULABLE.encode()]
            per_node.append(0)
        elif name == "NodeName":
            lut = [taints.ERR_NODE_NAME.encode()]
            per_node.append(0)
        elif name == "NodePorts":
            lut = [ports.ERR_NODE_PORTS.encode()]
            per_node.append(0)
        elif name == "TaintToleration":
            stride = max((len(t) for t in table.taints), default=0)
            if stride == 0:
                lut = [b""] * n  # never indexed (no taints -> no failures)
                stride = 1
            else:
                lut = []
                for j in range(n):
                    for ti in range(stride):
                        if ti < len(table.taints[j]):
                            key, value, _ = table.taints[j][ti]
                            lut.append(
                                ("node(s) had untolerated taint {%s: %s}" % (key, value)).encode()
                            )
                        else:
                            lut.append(b"")
            per_node.append(1)
        elif name == "PodTopologySpread":
            lut = []
            for code in range(1, 2 * topologyspread.MAX_CONSTRAINTS + 1):
                lut.append(
                    (topologyspread.ERR_MISSING_LABEL if code % 2 == 1
                     else topologyspread.ERR_SKEW).encode()
                )
            per_node.append(0)
        elif name == "InterPodAffinity":
            lut = [interpod.ERR_AFFINITY.encode(), interpod.ERR_ANTI_AFFINITY.encode(),
                   interpod.ERR_EXISTING_ANTI.encode()]
            per_node.append(0)
        elif name == "VolumeRestrictions":
            lut = [volumerestrictions.ERR_DISK_CONFLICT.encode()]
            per_node.append(0)
        elif name == "NodeVolumeLimits":
            lut = [nodevolumelimits.ERR_MAX_VOLUME_COUNT.encode()]
            per_node.append(0)
        elif name == "VolumeBinding":
            # codes are a bitmask (1 node-conflict | 2 bind-conflict |
            # 4 pv-not-exist); decode_filter renders every combination
            lut = [volumebinding.decode_filter(c, 0, None).encode() for c in range(1, 8)]
            per_node.append(0)
        elif name == "VolumeZone":
            lut = [volumezone.ERR_VOLUME_ZONE_CONFLICT.encode()]
            per_node.append(0)
        elif name in cw.host.get("custom_msgs", {}):
            lut = [m.encode() for m in cw.host["custom_msgs"][name]] or [b""]
            per_node.append(0)
        else:
            return None
        luts.append(lut)

    lut_flat: list[bytes] = []
    lut_off = [0]
    for lut in luts:
        lut_flat.extend(lut)
        lut_off.append(len(lut_flat))

    names_sorted = np.argsort(np.asarray(table.names)).astype(np.int32)
    sorted_filters = (np.argsort(np.asarray(filter_names)).astype(np.int32)
                      if filter_names else np.zeros(0, np.int32))
    sorted_scores = (np.argsort(np.asarray(score_names)).astype(np.int32)
                     if score_names else np.zeros(0, np.int32))
    lut_off_arr = np.asarray(lut_off, dtype=np.int32)
    per_node_arr = np.asarray(per_node, dtype=np.uint8)
    # score finalization params (the hostnorm.finalize_chunk dispatch,
    # matched by NAME exactly as finalize_chunk does)
    _KINDS = {"NodeAffinity": 1, "TaintToleration": 2,
              "PodTopologySpread": 3, "InterPodAffinity": 4}
    kinds = np.asarray([_KINDS.get(nm, 0) for nm in score_names], np.int32)
    weights = np.asarray([cw.config.weight(nm) for nm in score_names], np.int64)
    # the C context copies every fragment (escaped node/plugin keys, escaped
    # LUT messages) into its own storage, so the Python arrays above only
    # need to live for this call
    cptr = lib.codec_ctx_new(
        n, len(filter_names), len(score_names),
        _c_str_array([nm.encode() for nm in table.names]),
        _c_str_array([nm.encode() for nm in filter_names]),
        _c_str_array([nm.encode() for nm in score_names]),
        _i32p(np.ascontiguousarray(names_sorted)),
        _i32p(np.ascontiguousarray(sorted_filters)),
        _i32p(np.ascontiguousarray(sorted_scores)),
        _c_str_array(lut_flat or [b""]),
        _i32p(lut_off_arr), _u8p(per_node_arr),
        _i32p(kinds), _i64p(weights), int(topologyspread._BIG),
    )
    ctx = _NativeCtx(lib, cptr, n)
    # per-pod plugin-ran / score-skip rows for the fused path (row slices
    # hand C a contiguous [F]/[S] uint8 pointer without per-pod rebuilds)
    fskip = cw.host.get("filter_skip", {})
    sskip = cw.host.get("score_skip", {})
    p = cw.n_pods
    ctx.active_rows = np.ascontiguousarray(
        ~np.stack([np.asarray(fskip[nm], bool) for nm in filter_names], axis=1)
        if filter_names else np.zeros((p, 0), bool), np.uint8)
    ctx.sskip_rows = np.ascontiguousarray(
        np.stack([np.asarray(sskip[nm], bool) for nm in score_names], axis=1)
        if score_names else np.zeros((p, 0), bool), np.uint8)
    ctx.has_tsp_score = "PodTopologySpread" in score_names
    return ctx


class _NativeCtx:
    """Owns one C-side codec context; freed with the workload."""

    __slots__ = ("lib", "ptr", "n", "active_rows", "sskip_rows",
                 "has_tsp_score", "take", "peek", "__weakref__")

    def __init__(self, lib, ptr, n):
        self.lib = lib
        self.ptr = ptr
        self.n = n
        self.active_rows = None
        self.sskip_rows = None
        self.has_tsp_score = False
        # blob -> str builder: plain memcpy when the ctx proves every
        # emitted byte ASCII, else the UTF-8-validating decode
        all_ascii = lib.ctx_all_ascii(ptr)
        self.take = (take_sized_string_ascii if all_ascii
                     else take_sized_string)
        # arena variant (no free; ctx_decode_chunk's arena is released
        # in one chunk_arena_free after the whole chunk's strs exist)
        self.peek = peek_string_ascii if all_ascii else peek_string

    def __del__(self):
        if self.ptr:
            self.lib.codec_ctx_free(self.ptr)
            self.ptr = None


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def encode_filter(ctx: _NativeCtx, codes: np.ndarray, active: np.ndarray) -> str:
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    active = np.ascontiguousarray(active, dtype=np.uint8)
    out_len = ctypes.c_int64()
    ptr = ctx.lib.ctx_encode_filter(ctx.ptr, _i32p(codes), _u8p(active),
                                    ctypes.byref(out_len))
    return ctx.take(ctx.lib, ptr, out_len.value)


def encode_scores(ctx: _NativeCtx, values: np.ndarray, sskip: np.ndarray,
                  feasible: np.ndarray) -> str:
    values = np.ascontiguousarray(values, dtype=np.int64)
    sskip = np.ascontiguousarray(sskip, dtype=np.uint8)
    feasible = np.ascontiguousarray(feasible, dtype=np.uint8)
    out_len = ctypes.c_int64()
    ptr = ctx.lib.ctx_encode_scores(ctx.ptr, _i64p(values), _u8p(sskip),
                                    _u8p(feasible), ctypes.byref(out_len))
    return ctx.take(ctx.lib, ptr, out_len.value)


def _tsp_ignored_cached(rr, ci: int, c: int):
    """PodTopologySpread's [C, N] score-ignore mask for compact chunk ci,
    cached on the ReplayResult (shared by the per-pod fused path and the
    chunk call; double-checked under the recon lock so a chunk boundary
    doesn't make every pool worker recompute the O(C*N) mask at once)."""
    cache = getattr(rr, "_fused_ignored", None)
    if cache is None or cache[0] != ci:
        with rr._recon_lock:
            cache = getattr(rr, "_fused_ignored", None)
            if cache is None or cache[0] != ci:
                ig = np.ascontiguousarray(
                    rr._tsp_ignored_chunk(ci, c, rr.cw.n_nodes), np.uint8)
                cache = (ci, ig)
                rr._fused_ignored = cache
    return cache[1]


class _ChunkHandle:
    """An in-flight ctx_decode_chunk result: the arena pointer plus the
    per-pod blob address/length arrays.  decode_chunk_take() turns it
    into strs and frees the arena; dropping it without take leaks the
    arena (callers always pair the two)."""

    __slots__ = ("ctx", "arena", "out_ptrs", "out_lens", "skip", "c",
                 "thread_seconds", "_keep")

    def __init__(self, ctx, arena, out_ptrs, out_lens, skip, c,
                 thread_seconds, keep):
        self.ctx = ctx
        self.arena = arena
        self.out_ptrs = out_ptrs
        self.out_lens = out_lens
        self.skip = skip
        self.c = c
        self.thread_seconds = thread_seconds
        self._keep = keep

    def discard(self) -> None:
        """Free the arena without building any strings — the error-path
        cleanup (decode_chunk_take does this in its finally on the
        normal path)."""
        if self.arena is not None:
            self.ctx.lib.chunk_arena_free(self.arena)
            self.arena = None


def decode_chunk_start(ctx: _NativeCtx, rr, lo: int, hi: int,
                       skip=None, n_threads: int | None = None) -> _ChunkHandle:
    """The GIL-released half of the chunk decode: one ctx_decode_chunk
    call covering pods lo..hi (a range inside ONE compact replay chunk).
    The C side iterates the pods with its worker pool and emits every
    pod's three heavy blobs into a per-call arena.  Runs fine on a helper
    thread (ctypes drops the GIL for the call) — decode_release_batches
    pipelines the NEXT batch's C decode under the current batch's
    str-building this way.

    skip: optional [hi-lo] uint8 — pods Python's prefilter-reject
    early-out owns; the C side leaves their slots empty."""
    from ..framework.pipeline import PACK_MODES
    from ..utils.platform import effective_cpu_count

    cc = rr._compact
    c = hi - lo
    ci, r_lo = divmod(lo, cc.chunk)
    # cc.host(): device-resident chunks materialize here — the memoized
    # D2H this read path exists to defer (framework/replay.py)
    packed = cc.host("packed", ci)
    if not packed.flags["C_CONTIGUOUS"]:
        # device-layout fetch (TPU backends can return strided host
        # arrays); the C codec walks raw pointers in C order
        packed = cc.packed[ci] = np.ascontiguousarray(packed)
    code_bits = PACK_MODES[cc.pack_mode][1]
    n = ctx.n
    elem = packed.dtype.itemsize
    packed_ptr = packed.ctypes.data + r_lo * n * elem

    active = ctx.active_rows[lo:hi]   # [c, F], contiguous row slice
    sskip = ctx.sskip_rows[lo:hi]     # [c, S]
    want = np.ascontiguousarray(
        np.asarray(rr.feasible_count[lo:hi]) > 1, np.uint8)

    s = len(cc.score_cols)
    col_base = (ctypes.c_void_p * max(s, 1))()
    col_stride = (ctypes.c_int64 * max(s, 1))()
    col_elem = (ctypes.c_int32 * max(s, 1))()
    keep_alive = [packed, active, sskip, want]
    any_scores = bool(want.any())
    if any_scores and s:
        static_rows = rr.cw.host.get("static_score_rows", {})
        for q, (group, row) in enumerate(cc.score_cols):
            if group == "host":
                # precompiled host-resident raw ([P, N] C-contiguous);
                # sskip'd scorers are never read by the C codec, so the
                # unmasked rows are safe to hand over
                src = static_rows[row]
                if not src.flags["C_CONTIGUOUS"]:
                    src = static_rows[row] = np.ascontiguousarray(src)
                keep_alive.append(src)
                e = src.dtype.itemsize
                col_base[q] = src.ctypes.data + lo * n * e
                col_stride[q] = n * e
                col_elem[q] = e
            else:
                arr = cc.host(group, ci)       # [C, S_g, N]
                if not arr.flags["C_CONTIGUOUS"]:
                    arr = np.ascontiguousarray(arr)
                    getattr(cc, group)[ci] = arr
                keep_alive.append(arr)
                e = arr.dtype.itemsize
                col_base[q] = arr.ctypes.data + (r_lo * arr.shape[1] + row) * n * e
                col_stride[q] = arr.shape[1] * n * e
                col_elem[q] = e

    ig_ptr = None
    if (any_scores and ctx.has_tsp_score
            and rr.cw.host.get("tsp_ignore") is not None):
        ig = _tsp_ignored_cached(rr, ci, packed.shape[0])
        ig_rows = ig[r_lo:r_lo + c]
        keep_alive.append(ig_rows)
        ig_ptr = _u8p(ig_rows)

    out_ptrs = np.zeros(c * 3, np.int64)
    out_lens = np.zeros(c * 3, np.int64)
    tsec = ctypes.c_double()
    if n_threads is None:
        n_threads = min(8, effective_cpu_count())
    if skip is not None:
        keep_alive.append(skip)
    arena = ctx.lib.ctx_decode_chunk(
        ctx.ptr, c,
        ctypes.c_void_p(packed_ptr), elem, code_bits,
        _u8p(active), _u8p(sskip),
        col_base, col_stride, col_elem,
        ig_ptr, _u8p(want), _u8p(skip) if skip is not None else None,
        n_threads,
        _i64p(out_ptrs), _i64p(out_lens), ctypes.byref(tsec))
    return _ChunkHandle(ctx, arena, out_ptrs, out_lens, skip, c,
                        float(tsec.value), keep_alive)


def decode_chunk_take(handle: _ChunkHandle) -> list:
    """Blob strs from a decode_chunk_start handle; frees the arena.
    triples[i] is (filter_json, score_json | None, finalscore_json |
    None), or None where the skip mask was set."""
    ctx = handle.ctx
    peek = ctx.peek
    skip = handle.skip
    out_ptrs, out_lens = handle.out_ptrs, handle.out_lens
    try:
        triples: list = []
        for i in range(handle.c):
            if skip is not None and skip[i]:
                triples.append(None)
                continue
            b = 3 * i
            fj = peek(int(out_ptrs[b]), int(out_lens[b]))
            sj = (peek(int(out_ptrs[b + 1]), int(out_lens[b + 1]))
                  if out_ptrs[b + 1] else None)
            fnj = (peek(int(out_ptrs[b + 2]), int(out_lens[b + 2]))
                   if out_ptrs[b + 2] else None)
            triples.append((fj, sj, fnj))
    finally:
        handle.discard()
    return triples


def decode_chunk_fused(ctx: _NativeCtx, rr, lo: int, hi: int,
                       skip=None, n_threads: int | None = None):
    """decode_chunk_start + decode_chunk_take in one call.

    Returns (triples, native_thread_seconds)."""
    handle = decode_chunk_start(ctx, rr, lo, hi, skip=skip,
                                n_threads=n_threads)
    return decode_chunk_take(handle), handle.thread_seconds


def decode_pod_fused(ctx: _NativeCtx, rr, i: int, hi: int,
                     want_scores: bool) -> tuple[str, str | None, str | None]:
    """(filter-result, score-result, finalscore-result) for pod i straight
    from the compact replay layout — one C call; no [F,N] code unpack, no
    int64 raw/final materialization, normalization computed in C
    (hostnorm mirror, asserted byte-identical by tests/test_native_codec.py).

    i indexes the compact chunks; hi indexes the workload's per-pod host
    tables (they differ only on the extender's single-row replays, which
    never take this path)."""
    from ..framework.pipeline import PACK_MODES

    cc = rr._compact
    ci, r = divmod(i, cc.chunk)
    # cc.host(): device-resident chunks materialize here (memoized D2H)
    packed = cc.host("packed", ci)
    if not packed.flags["C_CONTIGUOUS"]:
        # device-layout fetch (TPU backends can return strided host
        # arrays); the C codec walks raw pointers in C order
        packed = cc.packed[ci] = np.ascontiguousarray(packed)
    code_bits = PACK_MODES[cc.pack_mode][1]
    prow = packed[r]

    s = len(cc.score_cols)
    col_ptrs = (ctypes.c_void_p * s)()
    col_elem = (ctypes.c_int32 * s)()
    cols_alive = []
    if want_scores:
        static_rows = rr.cw.host.get("static_score_rows", {})
        for q, (group, row) in enumerate(cc.score_cols):
            if group == "host":
                # precompiled host-resident raw ([P, N] C-contiguous
                # numpy); sskip'd scorers are never read by the C codec,
                # so the unmasked row is safe to hand over
                src = static_rows[row]
                col = src[hi]
                cols_alive.append(col)
                col_ptrs[q] = col.ctypes.data
                col_elem[q] = src.dtype.itemsize
                continue
            arr = cc.host(group, ci)
            if not arr.flags["C_CONTIGUOUS"]:
                arr = np.ascontiguousarray(arr)
                getattr(cc, group)[ci] = arr
            col = arr[r, row]
            cols_alive.append(col)
            col_ptrs[q] = col.ctypes.data
            col_elem[q] = arr.dtype.itemsize

    ignored_ptr = None
    if want_scores and ctx.has_tsp_score and rr.cw.host.get("tsp_ignore") is not None:
        ig_row = _tsp_ignored_cached(rr, ci, packed.shape[0])[r]
        ignored_ptr = _u8p(ig_row)

    out_blobs = (ctypes.c_void_p * 3)()
    out_lens = (ctypes.c_int64 * 3)()
    ctx.lib.ctx_decode_pod(
        ctx.ptr,
        prow.ctypes.data_as(ctypes.c_void_p), packed.dtype.itemsize, code_bits,
        _u8p(ctx.active_rows[hi]), _u8p(ctx.sskip_rows[hi]),
        col_ptrs, col_elem, ignored_ptr, 1 if want_scores else 0,
        out_blobs, out_lens,
    )
    filter_json = ctx.take(ctx.lib, out_blobs[0], out_lens[0])
    score_json = final_json = None
    if out_blobs[1]:
        score_json = ctx.take(ctx.lib, out_blobs[1], out_lens[1])
    if out_blobs[2]:
        final_json = ctx.take(ctx.lib, out_blobs[2], out_lens[2])
    return filter_json, score_json, final_json


def encode_string_map(d: dict[str, str]) -> str | None:
    """marshal(d) for a flat str->str dict via the native escape pass —
    the result-history record encoder.  None when the codec is
    unavailable (caller falls back to the Python marshal).

    The str is built in ONE sized copy (memmove when the C side proves
    the output pure ASCII): the record is re-encoded once per pod per
    wave over ~250KB of blob values, so the NUL-scan + bytes round-trip
    of the plain take_string path was a real slice of commit time."""
    lib = get_lib()
    if lib is None:
        return None
    items = sorted(d.items())
    keys = _c_str_array([k.encode() for k, _ in items])
    vals_b = [v.encode() for _, v in items]
    vals = _c_str_array(vals_b)
    lens = (ctypes.c_longlong * len(items))(*[len(b) for b in vals_b])
    out_len = ctypes.c_longlong()
    ascii_only = ctypes.c_int32()
    ptr = lib.encode_string_map_sized(keys, vals, lens, len(items),
                                      ctypes.byref(out_len),
                                      ctypes.byref(ascii_only))
    take = take_sized_string_ascii if ascii_only.value else take_sized_string
    return take(lib, ptr, out_len.value)
