"""Native fast path for the annotation decoder.

Builds per-workload context (name arrays, sorted orders, message LUTs) for
native/annotation_codec.cpp and encodes the three heavy blobs
(filter-result, score-result, finalscore-result) in C++.  Used by
store/decode.py when the native codec is available; output is
byte-identical to the Python path (asserted by tests/test_native_codec.py).
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..native import get_lib, take_string
from ..plugins import (
    affinity, interpod, nodevolumelimits, ports, taints, topologyspread,
    volumebinding, volumerestrictions, volumezone,
)
from ..plugins.noderesources import decode_fit_filter

_MAX_FIT_LUT_BITS = 16


def _c_str_array(strings: list[bytes]):
    arr = (ctypes.c_char_p * len(strings))(*strings)
    return arr


def build_context(cw):
    """-> context dict or None when a plugin's messages can't be LUT'd."""
    lib = get_lib()
    if lib is None:
        return None
    table = cw.node_table
    n = table.n
    filter_names = cw.config.filters()
    score_names = cw.config.scorers()

    luts: list[list[bytes]] = []
    per_node: list[int] = []
    for name in filter_names:
        if name == "NodeResourcesFit":
            bits = cw.schema.n + 1
            if bits > _MAX_FIT_LUT_BITS:
                return None
            lut = [
                decode_fit_filter(code, cw.schema).encode()
                for code in range(1, (1 << bits))
            ]
            per_node.append(0)
        elif name == "NodeAffinity":
            lut = [affinity.ERR_REASON.encode()]
            per_node.append(0)
        elif name == "NodeUnschedulable":
            lut = [taints.ERR_UNSCHEDULABLE.encode()]
            per_node.append(0)
        elif name == "NodeName":
            lut = [taints.ERR_NODE_NAME.encode()]
            per_node.append(0)
        elif name == "NodePorts":
            lut = [ports.ERR_NODE_PORTS.encode()]
            per_node.append(0)
        elif name == "TaintToleration":
            stride = max((len(t) for t in table.taints), default=0)
            if stride == 0:
                lut = [b""] * n  # never indexed (no taints -> no failures)
                stride = 1
            else:
                lut = []
                for j in range(n):
                    for ti in range(stride):
                        if ti < len(table.taints[j]):
                            key, value, _ = table.taints[j][ti]
                            lut.append(
                                ("node(s) had untolerated taint {%s: %s}" % (key, value)).encode()
                            )
                        else:
                            lut.append(b"")
            per_node.append(1)
        elif name == "PodTopologySpread":
            lut = []
            for code in range(1, 2 * topologyspread.MAX_CONSTRAINTS + 1):
                lut.append(
                    (topologyspread.ERR_MISSING_LABEL if code % 2 == 1
                     else topologyspread.ERR_SKEW).encode()
                )
            per_node.append(0)
        elif name == "InterPodAffinity":
            lut = [interpod.ERR_AFFINITY.encode(), interpod.ERR_ANTI_AFFINITY.encode(),
                   interpod.ERR_EXISTING_ANTI.encode()]
            per_node.append(0)
        elif name == "VolumeRestrictions":
            lut = [volumerestrictions.ERR_DISK_CONFLICT.encode()]
            per_node.append(0)
        elif name == "NodeVolumeLimits":
            lut = [nodevolumelimits.ERR_MAX_VOLUME_COUNT.encode()]
            per_node.append(0)
        elif name == "VolumeBinding":
            # codes are a bitmask (1 node-conflict | 2 bind-conflict |
            # 4 pv-not-exist); decode_filter renders every combination
            lut = [volumebinding.decode_filter(c, 0, None).encode() for c in range(1, 8)]
            per_node.append(0)
        elif name == "VolumeZone":
            lut = [volumezone.ERR_VOLUME_ZONE_CONFLICT.encode()]
            per_node.append(0)
        elif name in cw.host.get("custom_msgs", {}):
            lut = [m.encode() for m in cw.host["custom_msgs"][name]] or [b""]
            per_node.append(0)
        else:
            return None
        luts.append(lut)

    lut_flat: list[bytes] = []
    lut_off = [0]
    for lut in luts:
        lut_flat.extend(lut)
        lut_off.append(len(lut_flat))

    names_sorted = np.argsort(np.asarray(table.names)).astype(np.int32)
    ctx = {
        "lib": lib,
        "n": n,
        "node_names": _c_str_array([nm.encode() for nm in table.names]),
        "filter_names": _c_str_array([nm.encode() for nm in filter_names]),
        "score_names": _c_str_array([nm.encode() for nm in score_names]),
        "sorted_nodes": np.ascontiguousarray(names_sorted),
        "sorted_filters": np.argsort(np.asarray(filter_names)).astype(np.int32)
        if filter_names else np.zeros(0, np.int32),
        "sorted_scores": np.argsort(np.asarray(score_names)).astype(np.int32)
        if score_names else np.zeros(0, np.int32),
        "lut_flat": _c_str_array(lut_flat or [b""]),
        "lut_off": np.asarray(lut_off, dtype=np.int32),
        "per_node": np.asarray(per_node, dtype=np.uint8),
    }
    return ctx


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def encode_filter(ctx, codes: np.ndarray, active: np.ndarray) -> str:
    lib = ctx["lib"]
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    active = np.ascontiguousarray(active, dtype=np.uint8)
    ptr = lib.encode_filter_result(
        ctx["n"], codes.shape[0],
        _i32p(codes), _u8p(active),
        ctx["node_names"], ctx["filter_names"],
        _i32p(ctx["sorted_nodes"]), _i32p(ctx["sorted_filters"]),
        ctx["lut_flat"], _i32p(ctx["lut_off"]), _u8p(ctx["per_node"]),
    )
    return take_string(lib, ptr)


def encode_scores(ctx, values: np.ndarray, sskip: np.ndarray, feasible: np.ndarray) -> str:
    lib = ctx["lib"]
    values = np.ascontiguousarray(values, dtype=np.int64)
    sskip = np.ascontiguousarray(sskip, dtype=np.uint8)
    feasible = np.ascontiguousarray(feasible, dtype=np.uint8)
    ptr = lib.encode_score_result(
        ctx["n"], values.shape[0],
        _i64p(values), _u8p(sskip), _u8p(feasible),
        ctx["node_names"], ctx["score_names"],
        _i32p(ctx["sorted_nodes"]), _i32p(ctx["sorted_scores"]),
    )
    return take_string(lib, ptr)


def encode_string_map(d: dict[str, str]) -> str | None:
    """marshal(d) for a flat str->str dict via the native escape pass —
    the result-history record encoder.  None when the codec is
    unavailable (caller falls back to the Python marshal)."""
    lib = get_lib()
    if lib is None:
        return None
    items = sorted(d.items())
    keys = _c_str_array([k.encode() for k, _ in items])
    vals_b = [v.encode() for _, v in items]
    vals = _c_str_array(vals_b)
    lens = (ctypes.c_longlong * len(items))(*[len(b) for b in vals_b])
    ptr = lib.encode_string_map(keys, vals, lens, len(items))
    return take_string(lib, ptr)
