from .annotations import *  # noqa: F401,F403
from .decode import decode_pod_result, decode_all  # noqa: F401
