"""Scenario runner: execute KEP-140 scenarios against the cluster store.

Design source: keps/140-scenario-based-simulation/README.md — the
ScenarioStep clock ("What happens in a single MajorStep"): at each
MajorStep, (1) the step's spec.operations are applied (each successful
resource change advances MinorStep), (2) the SimulationController — here
the tensor scheduler engine — runs until it "can no longer do anything
with the current cluster state", (3) generated events (PodScheduled) are
appended to the result timeline, (4) if the step carries a
DoneOperation the scenario becomes Succeeded; after the last step
without one it becomes Paused (more operations may be added).

Operations are exactly the KEP's four: createOperation, patchOperation
(JSON merge patch, RFC 7386 — the KEP's PatchType default),
deleteOperation, doneOperation.  An operation with zero or multiple of
these set fails the scenario, as specified.
"""

from __future__ import annotations

import copy
import threading

from ..cluster.store import ApiError, ObjectStore
from .types import (
    KIND_TO_RESOURCE,
    PHASE_FAILED,
    PHASE_PAUSED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    STEP_COMPLETED,
    STEP_CONTROLLER_COMPLETED,
    STEP_CONTROLLER_RUNNING,
    STEP_OPERATING,
)

SIMULATOR_VERSION = "kube-scheduler-simulator-tpu/0.1"


class _Cancelled(Exception):
    """The scenario was deleted (or replaced) while its worker ran."""

_OP_FIELDS = ("createOperation", "patchOperation", "deleteOperation", "doneOperation")


def merge_patch(target, patch):
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    if not isinstance(target, dict):
        target = {}
    out = copy.deepcopy(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


def _op_kind(op: dict) -> str:
    present = [f for f in _OP_FIELDS if op.get(f) is not None]
    if len(present) != 1:
        raise ValueError(
            "operation must set exactly one of createOperation/patchOperation/"
            f"deleteOperation/doneOperation, got {present or 'none'}"
        )
    return present[0]


def _resource_for(type_meta: dict) -> str:
    kind = (type_meta or {}).get("kind") or ""
    resource = KIND_TO_RESOURCE.get(kind)
    if resource is None:
        raise ValueError(f"unsupported kind {kind!r} in scenario operation")
    return resource


class ScenarioService:
    """Holds named scenarios; runs each in a worker thread against the
    store + engine (the KEP's scenario controller + SimulationController
    loop).  The engine is optional — without one, steps only apply
    operations (useful for pure state manipulation)."""

    def __init__(self, store: ObjectStore, engine=None):
        self.store = store
        self.engine = engine
        self._lock = threading.Lock()
        self._scenarios: dict[str, dict] = {}
        self._threads: dict[str, threading.Thread] = {}
        # generation token per live scenario: a worker only writes status/
        # timeline while its token is still current, so deleting a running
        # scenario (and recreating the name) orphans the old worker
        # instead of letting it corrupt the new one
        self._gens: dict[str, object] = {}

    # ------------------------------------------------------------- CRUD

    def create(self, scenario: dict, run: bool = True) -> dict:
        name = ((scenario.get("metadata") or {}).get("name")) or ""
        if not name:
            raise ValueError("scenario needs metadata.name")
        with self._lock:
            if name in self._scenarios:
                raise ValueError(f"scenario {name!r} already exists")
            sc = copy.deepcopy(scenario)
            sc.setdefault("kind", "Scenario")
            sc.setdefault("apiVersion", "simulation.sigs.k8s.io/v1alpha1")
            sc["status"] = {
                "phase": PHASE_PENDING,
                "stepStatus": {"step": {"major": 0, "minor": 0}, "phase": ""},
                "scenarioResult": {
                    "simulatorVersion": SIMULATOR_VERSION,
                    "timeline": {},
                },
            }
            self._scenarios[name] = sc
            token = object()
            self._gens[name] = token
            if run:
                t = threading.Thread(target=self.run, args=(name, token), daemon=True)
                self._threads[name] = t
        if run:
            t.start()
        return copy.deepcopy(sc)

    def get(self, name: str) -> dict:
        with self._lock:
            sc = self._scenarios.get(name)
            if sc is None:
                raise KeyError(name)
            return copy.deepcopy(sc)

    def list(self) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(s) for s in self._scenarios.values()]

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._scenarios:
                raise KeyError(name)
            del self._scenarios[name]
            # invalidating the token also cancels the worker at its next
            # step boundary
            self._gens.pop(name, None)
            self._threads.pop(name, None)

    def wait(self, name: str, timeout: float | None = 60) -> dict:
        t = self._threads.get(name)
        if t is not None:
            t.join(timeout)
        return self.get(name)

    # ------------------------------------------------------------- run

    def run(self, name: str, token: object | None = None) -> dict:
        """Execute the scenario to completion (synchronously)."""
        with self._lock:
            sc = self._scenarios.get(name)
            if sc is None:
                raise KeyError(name)
            if token is None:
                token = self._gens.get(name)
            ops = copy.deepcopy((sc.get("spec") or {}).get("operations") or [])
            status = sc["status"]
            status["phase"] = PHASE_RUNNING

        try:
            done = self._run_steps(name, token, ops)
        except _Cancelled:
            return {}
        except Exception as e:
            self._set_status(name, token, phase=PHASE_FAILED, message=str(e))
            return self.get(name)
        self._set_status(
            name, token,
            phase=PHASE_SUCCEEDED if done else PHASE_PAUSED,
            message=None if done else
            "all operations finished without a doneOperation; "
            "operations can still be added",
        )
        try:
            return self.get(name)
        except KeyError:
            return {}

    # ------------------------------------------------------------ steps

    def _set_status(self, name: str, token, phase=None, message=None,
                    step=None, step_phase=None):
        with self._lock:
            sc = self._scenarios.get(name)
            if sc is None or self._gens.get(name) is not token:
                return  # deleted or replaced: the stale worker stays silent
            st = sc["status"]
            if phase is not None:
                st["phase"] = phase
            st["message"] = message
            if step is not None:
                st["stepStatus"]["step"] = step
            if step_phase is not None:
                st["stepStatus"]["phase"] = step_phase

    def _append_timeline(self, name: str, token, major: int, event: dict):
        with self._lock:
            sc = self._scenarios.get(name)
            if sc is None or self._gens.get(name) is not token:
                return
            tl = sc["status"]["scenarioResult"]["timeline"]
            tl.setdefault(str(major), []).append(event)

    def _check_live(self, name: str, token) -> None:
        with self._lock:
            if self._gens.get(name) is not token:
                raise _Cancelled(name)

    def _run_steps(self, name: str, token, ops: list[dict]) -> bool:
        by_step: dict[int, list[dict]] = {}
        for i, op in enumerate(ops):
            op.setdefault("id", f"op-{i}")
            by_step.setdefault(int(op.get("step") or 0), []).append(op)

        for major in sorted(by_step):
            self._check_live(name, token)  # cancelled by delete()
            minor = 0
            self._set_status(name, token, step={"major": major, "minor": minor},
                             step_phase=STEP_OPERATING)
            done_requested = False
            for op in by_step[major]:
                self._check_live(name, token)
                field = _op_kind(op)  # raises -> scenario Failed
                if field == "doneOperation":
                    done_requested = True
                    self._append_timeline(name, token, major, {
                        "id": op["id"],
                        "step": {"major": major, "minor": minor},
                        "done": {"operation": op["doneOperation"]},
                    })
                    continue
                minor += self._apply_op(name, token, major, minor, op, field)

            # SimulationController (the scheduler) runs to quiescence
            if self.engine is not None:
                self._set_status(name, token, step_phase=STEP_CONTROLLER_RUNNING)
                minor = self._run_controller(name, token, major, minor)
                self._set_status(name, token, step_phase=STEP_CONTROLLER_COMPLETED)

            self._set_status(name, token, step={"major": major, "minor": minor},
                             step_phase=STEP_COMPLETED)
            if done_requested:
                return True
        return False

    def _apply_op(self, name, token, major, minor, op, field) -> int:
        """Apply one create/patch/delete operation; returns 1 if a resource
        changed (MinorStep advances on every resource operation)."""
        body = op[field]
        if field == "createOperation":
            obj = body.get("object") or {}
            resource = _resource_for(obj)
            result = self.store.create(resource, obj)
            self._append_timeline(name, token, major, {
                "id": op["id"], "step": {"major": major, "minor": minor},
                "create": {"operation": body, "result": result},
            })
            return 1
        meta = body.get("objectMeta") or {}
        resource = _resource_for(body.get("typeMeta"))
        if field == "patchOperation":
            cur = self.store.get(resource, meta.get("name"), meta.get("namespace"))
            import json as _json

            patch = body.get("patch")
            patch_obj = _json.loads(patch) if isinstance(patch, str) else (patch or {})
            new = merge_patch(cur, patch_obj)
            # identity is immutable under patch
            new.setdefault("metadata", {})["name"] = cur["metadata"]["name"]
            if "namespace" in cur["metadata"]:
                new["metadata"]["namespace"] = cur["metadata"]["namespace"]
            new["metadata"]["resourceVersion"] = cur["metadata"].get("resourceVersion")
            result = self.store.update(resource, new)
            self._append_timeline(name, token, major, {
                "id": op["id"], "step": {"major": major, "minor": minor},
                "patch": {"operation": body, "result": result},
            })
            return 1
        # deleteOperation
        self.store.delete(resource, meta.get("name"), meta.get("namespace"))
        self._append_timeline(name, token, major, {
            "id": op["id"], "step": {"major": major, "minor": minor},
            "delete": {"operation": body},
        })
        return 1

    def _run_controller(self, name, token, major, minor) -> int:
        """Run the scheduler until it can no longer bind anything; emit a
        generated PodScheduled timeline event per newly-bound pod (the
        KEP's generated timeline entries)."""
        before = {
            (p["metadata"].get("namespace") or "default", p["metadata"]["name"])
            for p in self.store.list("pods")[0]
            if (p.get("spec") or {}).get("nodeName")
        }
        while True:
            n = self.engine.schedule_pending()
            if not n:
                break
        gen = 0
        for p in self.store.list("pods")[0]:
            key = (p["metadata"].get("namespace") or "default", p["metadata"]["name"])
            if (p.get("spec") or {}).get("nodeName") and key not in before:
                self._append_timeline(name, token, major, {
                    "id": f"generated-{major}-{minor}",
                    "step": {"major": major, "minor": minor},
                    "podScheduled": {
                        "pod": f"{key[0]}/{key[1]}",
                        "node": p["spec"]["nodeName"],
                    },
                })
                minor += 1
                gen += 1
        return minor
