"""Scenario-based simulation (KEP-140).

The reference ships only a kubebuilder scaffold for this (an empty
Scenario CRD and a no-op Reconcile,
scenario/api/v1alpha1/scenario_types.go:27-64,
scenario/internal/controller/scenario_controller.go); the real design
lives in keps/140-scenario-based-simulation/README.md.  This package
implements that design against the simulator's cluster store: Scenario
specs with per-MajorStep create/patch/delete/done operations, the
scheduler engine as the SimulationController run to quiescence each
step, and a ScenarioResult timeline recording every operation plus
generated PodScheduled events.
"""

from .runner import ScenarioService, merge_patch
from .types import (
    PHASE_FAILED,
    PHASE_PAUSED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
)

__all__ = [
    "ScenarioService", "merge_patch",
    "PHASE_PENDING", "PHASE_RUNNING", "PHASE_PAUSED",
    "PHASE_SUCCEEDED", "PHASE_FAILED",
]
