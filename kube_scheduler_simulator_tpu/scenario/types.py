"""Scenario phases and step phases (KEP-140,
keps/140-scenario-based-simulation/README.md ScenarioPhase/StepPhase)."""

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_PAUSED = "Paused"      # all operations done but no DoneOperation yet
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"
PHASE_UNKNOWN = "Unknown"

STEP_OPERATING = "Operating"
STEP_OPERATING_COMPLETED = "OperatingCompleted"
STEP_CONTROLLER_RUNNING = "ControllerRunning"
STEP_CONTROLLER_COMPLETED = "ControllerCompleted"
STEP_COMPLETED = "Finished"

# resource-kind mapping for operation objects (kind -> store resource).
# PodGroup rides the generic-GVR registration (framework/gang.py
# ensure_podgroup_resource / config extraResources) — scenarios can
# create gangs directly (docs/gang-scheduling.md).
KIND_TO_RESOURCE = {
    "Namespace": "namespaces",
    "PriorityClass": "priorityclasses",
    "StorageClass": "storageclasses",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "Node": "nodes",
    "PersistentVolume": "persistentvolumes",
    "Pod": "pods",
    "PodGroup": "podgroups",
}
