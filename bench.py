#!/usr/bin/env python
"""Benchmark: scheduling-cycles/sec on the BASELINE configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Protocol (BASELINE.md): replay a pod queue; a completed scheduling cycle =
a pod through Filter -> Score -> Normalize -> select -> bind (the
reference counts Reserve reached).

The HEADLINE value is the END-TO-END throughput of the default config
(config 4, 10k pods x 5k nodes): warm steady-state replay with all result
tensors transferred to host — the annotations built from them ARE the
reference's product (storereflector write-back, SURVEY.md §3.2).  The
device-only number (results materialized on device, no host transfer) and
a full-annotation-decode figure are reported in `extra` along with a
config-5 (InterPodAffinity) run and an engine/serving-path measurement.

The CPU baseline divisor is the 16-way-parallel oracle
(reference_impl/parallel.py — the upstream Parallelizer fans Filter/Score
over 16 goroutines, so a single-threaded divisor would overstate the
speedup).  The sequential number is also measured for reference.  Both
run at --cpu-scale of the pod queue over the FULL node axis; per-cycle
CPU cost grows with queue position, so the reduced-scale CPU cycles/sec
OVERESTIMATES full-scale CPU throughput, keeping vs_baseline
conservative.  Known residual handicap: the oracle is Python, the
reference is Go — BASELINE.md discusses the gap.

A bit-parity gate (all five configs, --gate-scale) guards every number.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))


_HEARTBEAT = {"t": time.time()}


def log(*a):
    # once the hang watchdog owns recovery, the (possibly un-wedged) main
    # thread must stop at its next phase boundary: its remaining timed
    # phases would contend with the fallback child's own measurements on
    # this 1-core host.  The watchdog thread itself must not park here.
    import threading

    if (_HEARTBEAT.get("owner") == "watchdog"
            and threading.current_thread().name != "bench-hang-watchdog"):
        while True:
            time.sleep(60)
    _HEARTBEAT["t"] = time.time()
    print(*a, file=sys.stderr, flush=True)


def _try_claim(who: str) -> str:
    """Atomically claim the one-JSON-line right on shared stdout; returns
    the resulting owner ("run" = the normal path about to print, "crash"
    = the crash handler about to re-exec, "watchdog" = the hang watchdog's
    fallback child).  Exactly one JSON line may ever reach stdout."""
    import threading

    lock = _HEARTBEAT.setdefault("_lock", threading.Lock())
    with lock:
        if "owner" not in _HEARTBEAT:
            _HEARTBEAT["owner"] = who
        return _HEARTBEAT["owner"]


def _claim_stdout_or_park(who: str) -> None:
    """Claim stdout for `who`, or park this thread forever when the hang
    watchdog's fallback child already owns it (its _os._exit ends the
    process once the child finishes — a second JSON line would race it).
    A prior claim by "run" does NOT park a later "crash" claimant: that
    means the final print itself raised (e.g. BrokenPipeError), stdout is
    already broken or ours, and parking would hang with no child running."""
    if _try_claim(who) == "watchdog" and who != "watchdog":
        while True:
            time.sleep(60)


def _fallback_cmd(args) -> list[str]:
    """The reduced CPU-backend re-exec command, shared by the crash
    handler and the hang watchdog."""
    fwd = [sys.executable, __file__,
           "--config", str(args.config),
           "--scale", str(args.scale),
           "--cpu-scale", str(args.cpu_scale),
           "--cpu-node-scale", str(args.cpu_node_scale),
           "--gate-scale", "0.02",
           "--gate-configs", str(args.config),
           "--assume-fallback",
           "--seed", str(args.seed)]
    if args.smoke:
        fwd.append("--smoke")
    if args.skip_engine:
        fwd.append("--skip-engine")
    if args.skip_parity:
        fwd.append("--skip-parity")
    if args.skip_config5:
        fwd.append("--skip-config5")
    return fwd


def _start_hang_watchdog(args, stale_s: float = 1200) -> None:
    """The axon tunnel can wedge MID-CALL: a device op blocks in
    tcp_recvmsg forever and no exception ever raises (observed live —
    the crash re-exec path never fires).  A daemon thread watches the
    log() heartbeat; if nothing logs for stale_s, it re-execs the
    CPU-backend fallback in a fresh process and exits this one, so the
    driver's one-JSON-line contract survives even a silent tunnel death.
    stale_s is far above any legitimate gap between log lines (the
    longest is the under-cliff control's 900s subprocess timeout)."""
    import os as _os
    import subprocess as _sp
    import threading

    def run():
        while True:
            time.sleep(60)
            if _HEARTBEAT.get("owner"):
                return  # another path owns stdout/recovery now
            if time.time() - _HEARTBEAT["t"] > stale_s:
                if _try_claim("watchdog") != "watchdog":
                    return
                log(f"WATCHDOG: no progress for {stale_s:.0f}s — accelerator "
                    "tunnel wedged mid-call; re-running on the CPU backend "
                    "in a fresh process")
                env = {**_os.environ, "JAX_PLATFORMS": "cpu",
                       "KSS_BENCH_NO_REEXEC": "1"}
                r = _sp.run(_fallback_cmd(args), env=env)
                _os._exit(r.returncode)

    threading.Thread(target=run, daemon=True, name="bench-hang-watchdog").start()


_ORACLE_CHILD = """\
import json, resource, sys
# self-imposed address-space cap: a runaway oracle gets a MemoryError in
# its own process instead of inviting the kernel OOM killer to take the
# whole bench (round 4's exit 137, docs/bench/r04-tpu-bench.err).  Set
# here post-exec rather than via preexec_fn: running Python in a child
# forked from the JAX-multithreaded parent can deadlock before exec.
resource.setrlimit(resource.RLIMIT_AS, (4 << 30, 4 << 30))
sys.path.insert(0, {repo!r})
# hermetic CPU: the axon sitecustomize ignores JAX_PLATFORMS=cpu, and the
# oracle's plugin-helper imports pull jax in — force the CPU backend
# before anything can touch the (possibly wedged) tunnel
from kube_scheduler_simulator_tpu.utils.platform import force_cpu
force_cpu()
from kube_scheduler_simulator_tpu.models.workloads import baseline_config
from kube_scheduler_simulator_tpu.reference_impl.sequential import (
    SequentialScheduler)
nodes, pods, cfg = baseline_config({idx}, scale={scale}, seed={seed})
s = SequentialScheduler(nodes, pods, cfg)
w = sys.stdout
for pod in s.pods:
    anns, _ = s.schedule_one(pod)
    w.write(json.dumps(anns) + chr(10))
w.write("DONE " + str(len(s.pods)) + chr(10))
"""


def stream_oracle_parity(idx: int, scale: float, seed: int, chunk: int = 64,
                         want_digest: bool = False, heartbeat=None) -> dict:
    """Bit-parity check: device replay vs the sequential CPU oracle,
    both sides streamed so neither ever materializes the full annotation
    product (~13 GB at 10k x 5k).

    The oracle runs in ONE separate CPU-forced subprocess (address space
    self-capped via RLIMIT_AS) and streams one pod's annotations per
    line; this process decodes the same pod from the device replay and
    compares as lines arrive, holding one pod at a time.  Round 4 ran an
    8-worker parallel oracle in-process and the kernel OOM-killed the
    whole bench on the memory-starved TPU host (exit 137,
    docs/bench/r04-tpu-bench.err) — the parity machinery must never be
    able to take the measured process down with it.
    Parallel-vs-sequential oracle parity is covered by
    tests/test_parallel_oracle.py; the sequential oracle is the ground
    truth here (reference semantics: simulator/scheduler/plugin/
    wrappedplugin.go recording shim, resultstore/store.go score math).

    Returns {ok, pods, compared, keys_checked, mismatches,
    first_mismatch, sha256 (of every compared value, when want_digest),
    oracle_rc, oracle_err, oracle_seconds, replay_seconds}."""
    import hashlib
    import os as _os
    import subprocess as _sp
    import tempfile

    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=seed)
    t0 = time.time()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=chunk)
    replay_s = time.time() - t0
    h = hashlib.sha256() if want_digest else None
    out = {"ok": False, "pods": len(pods), "compared": 0, "keys_checked": 0,
           "mismatches": 0, "first_mismatch": None, "sha256": None,
           "oracle_rc": None, "oracle_err": "",
           "replay_seconds": round(replay_s, 1)}
    t0 = time.time()
    # child stderr goes to a temp file, not a pipe: this loop only drains
    # stdout, and a filled stderr pipe would deadlock the child mid-run
    with tempfile.TemporaryFile(mode="w+") as errf:
        child = _sp.Popen(
            [sys.executable, "-c",
             _ORACLE_CHILD.format(repo=str(Path(__file__).parent), idx=idx,
                                  scale=scale, seed=seed)],
            stdout=_sp.PIPE, stderr=errf, text=True,
            env={**_os.environ, "JAX_PLATFORMS": "cpu"},
        )
        i = 0
        done = False
        try:
            for line in child.stdout:
                if heartbeat is not None:
                    heartbeat(i)
                if line.startswith("DONE "):
                    done = int(line[5:]) == len(pods) == i
                    break
                sa = json.loads(line)
                da = decode_pod_result(rr, i)
                for k, v in sa.items():
                    out["keys_checked"] += 1
                    if h is not None:
                        h.update(v.encode())
                    # .get: a device-side MISSING key is a mismatch to
                    # record, not a KeyError that kills the whole check
                    if da.get(k, "\0missing") != v:
                        out["mismatches"] += 1
                        if out["first_mismatch"] is None:
                            out["first_mismatch"] = {
                                "pod": i, "key": k,
                                "dev": da.get(k, "<missing>")[:200],
                                "oracle": v[:200]}
                i += 1
                out["compared"] = i
        finally:
            # clean DONE: give the child a moment to exit on its own so
            # the artifact records its true rc (not a kill's -9)
            try:
                child.wait(timeout=10 if done else 0.1)
            except _sp.TimeoutExpired:
                child.kill()
                child.wait()
            errf.seek(0)
            out["oracle_err"] = errf.read().strip()[-300:]
    out["oracle_rc"] = child.returncode
    out["oracle_seconds"] = round(time.time() - t0, 1)
    out["ok"] = done and out["mismatches"] == 0
    if h is not None:
        out["sha256"] = h.hexdigest()
    if not done and out["mismatches"] == 0:
        out["oracle_died"] = True  # environment failure, not a parity one
    return out


def run_parity_gate(idx: int, scale: float, seed: int,
                    _retry: bool = True) -> bool:
    def hb(_i):
        _HEARTBEAT["t"] = time.time()  # streamed progress feeds watchdog

    r = stream_oracle_parity(idx, scale, seed, heartbeat=hb)
    if r["ok"]:
        return True
    if r["first_mismatch"]:
        m = r["first_mismatch"]
        log(f"PARITY MISMATCH config {idx} pod {m['pod']} key {m['key']}\n"
            f"  dev={m['dev']}\n  seq={m['oracle']}")
        return False
    # the oracle child died (rlimit MemoryError, OOM kill, crash) — that
    # is an environment failure, not a parity failure; shed load and
    # retry once at a smaller gate shape rather than reporting value 0
    log(f"parity-gate oracle child died at pod {r['compared']}/{r['pods']} "
        f"(rc={r['oracle_rc']}): {r['oracle_err']}")
    if _retry and scale > 0.011:
        log(f"  retrying gate config {idx} at scale {scale / 4}")
        return run_parity_gate(idx, scale / 4, seed, _retry=False)
    return False


def _available_gb() -> float:
    """MemAvailable from /proc/meminfo, in GiB (inf if unreadable)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) / (1 << 20)
    except OSError:
        pass
    return float("inf")


class _host_phase_ticker:
    """Touch the hang-watchdog heartbeat every 60s during a PURE-HOST
    phase (CPU oracle runs, subprocesses with their own timeouts).  Host
    phases cannot wedge on the accelerator tunnel, so keeping them alive
    is safe; device phases must only heartbeat on real progress
    (on_chunk), or a wedged device op would be masked."""

    def __enter__(self):
        import threading

        self._stop = threading.Event()

        def tick():
            while not self._stop.wait(60):
                _HEARTBEAT["t"] = time.time()

        self._t = threading.Thread(target=tick, daemon=True,
                                   name="bench-host-phase-ticker")
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        return False


def _device_initializes(timeout: float = 240) -> bool:
    """Probe device-backend init in a subprocess so a wedged accelerator
    tunnel cannot hang this process."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def measure_replay(idx: int, scale: float, seed: int, chunk: int, mesh_n: int,
                   decode_sample: int = 512, decode_stream: bool = True,
                   node_scale: float | None = None, quick: bool = False,
                   unroll: int = 2):
    """Compile + warm + timed device-only + timed end-to-end + timed
    ANNOTATIONS-MATERIALIZED end-to-end (decode of every pod's result
    annotations streamed on_chunk, overlapping device compute — the
    product semantics: the reference's reflector writes this JSON for
    every pod, storereflector.go:87-161) for one config."""
    import numpy as np

    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_release_batches

    nodes, pods, cfg = baseline_config(idx, scale=scale, seed=seed,
                                       node_scale=node_scale)
    log(f"config {idx}: {len(pods)} pods x {len(nodes)} nodes, plugins={cfg.enabled}")
    t0 = time.time()
    cw = compile_workload(nodes, pods, cfg)
    log(f"  compile_workload (host precompile): {time.time()-t0:.1f}s")

    mesh = None
    if mesh_n:
        from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh

        shards = mesh_n
        while shards > 1 and len(nodes) % shards:
            shards -= 1
        if shards > 1:
            mesh = make_mesh(shards, dp=1)
            log(f"  mesh: node axis sharded over {shards} devices")

    t0 = time.time()
    rr = replay(cw, chunk=chunk, collect=False, mesh=mesh,
                unroll=unroll)  # XLA compile + run
    log(f"  warm-up replay: {time.time()-t0:.1f}s, scheduled {rr.scheduled}/{len(pods)}")

    dev_cps = e2e_cps = None
    if not quick:  # quick: only the streamed-decode figure is wanted
        t0 = time.time()
        rr = replay(cw, chunk=chunk, collect=False, mesh=mesh, unroll=unroll)
        dev_s = time.time() - t0
        dev_cps = len(pods) / dev_s
        log(f"  device-only replay: {dev_s:.2f}s -> {dev_cps:,.0f} cycles/s")

        # best of 2: the tunneled link's bandwidth swings ~4x between runs;
        # the better run reflects transfer capability, not link luck
        e2e_s = None
        for attempt in range(2):
            t0 = time.time()
            rr = replay(cw, chunk=chunk, collect=True, mesh=mesh,
                        unroll=unroll)
            dt = time.time() - t0
            log(f"  incl host transfer of result tensors (run {attempt + 1}): "
                f"{dt:.2f}s -> {len(pods)/dt:,.0f} cycles/s")
            e2e_s = dt if e2e_s is None else min(e2e_s, dt)
        e2e_cps = len(pods) / e2e_s

    dec_cps = None
    if decode_sample:
        # release-style sample (the product semantics: the reflector
        # PATCHes each pod's annotations out and holds nothing) — holding
        # the whole sample resident would measure this host's page
        # backing, not the decoder
        ds = min(decode_sample, len(pods))
        sample = {"bytes": 0}

        def _sample_pod(i, a):
            if i == 0:
                sample["bytes"] = sum(len(v) for v in a.values())

        t0 = time.time()
        decode_release_batches(rr, 0, ds, on_pod=_sample_pod)
        dec_s = time.time() - t0
        sample_bytes = sample["bytes"]
        dec_cps = ds / dec_s
        log(f"  annotation decode ({ds}-pod sample, released per batch): "
            f"{dec_s:.2f}s -> {dec_cps:,.0f} pods/s decoded "
            f"(~{sample_bytes/1024:.0f} KiB/pod)")

    # annotations-materialized end-to-end: one replay with EVERY pod's 13
    # result annotations decoded to their final JSON strings, streamed as
    # chunks land so decode overlaps later chunks' device compute.  Each
    # pod's strings are released once built (their total length recorded),
    # matching the reference's reflector — it PATCHes the annotations out
    # and holds nothing (storereflector.go:87-161) — and keeping the
    # harness's live set out of this host's >8 GB page-backing cliff
    # (docs/bench/r04-host-page-backing.json), which is a property of the
    # bench host, not of the decoder.
    di_cps = None
    if decode_stream:
        import numpy as _np

        ann_bytes = _np.zeros(len(pods), dtype=_np.int64)  # idempotent per pod

        def _on_pod(i, a):
            ann_bytes[i] = sum(len(v) for v in a.values())

        def _consume(r, lo, hi):
            # release-per-batch (decode_release_batches docstring): the
            # reference reflector holds one pod's annotations at a time.
            # Each chunk landing is real end-to-end progress — feed the
            # hang watchdog so a long full-scale phase can't false-fire it
            _HEARTBEAT["t"] = time.time()
            decode_release_batches(r, lo, hi, on_pod=_on_pod)

        t0 = time.time()
        rr = replay(cw, chunk=chunk, collect=True, mesh=mesh, unroll=unroll,
                    on_chunk=_consume)
        di_s = time.time() - t0
        di_cps = len(pods) / di_s
        n_dec = int((ann_bytes > 0).sum())
        log(f"  e2e annotations materialized (streamed decode): {di_s:.2f}s "
            f"-> {di_cps:,.0f} cycles/s ({n_dec}/{len(pods)} pods decoded, "
            f"{ann_bytes.sum()/1e9:.1f} GB of annotation JSON built)")
    return {
        "pods": len(pods), "nodes": len(nodes),
        "device_only_cps": round(dev_cps, 1) if dev_cps else None,
        "incl_host_transfer_cps": round(e2e_cps, 1) if e2e_cps else None,
        "decode_inclusive_cps": round(di_cps, 1) if di_cps else None,
        "decode_pods_per_sec": round(dec_cps, 1) if dec_cps else None,
        "scheduled": rr.scheduled,
    }


def measure_engine(scale_pods: int, scale_nodes: int, seed: int,
                   interpod: bool = False, pipeline: bool = True,
                   gang_groups: int = 0, gang_members: int = 8):
    """Serving-path benchmark: ObjectStore -> SchedulerEngine.schedule_pending
    (compile -> replay -> decode -> commit, docs/wave-pipeline.md), with
    the tracer span breakdown.  interpod adds InterPodAffinity (the
    config-5 hard plugin) to the lineup and pod specs; pipeline=False
    forces the sequential post-pass commit (the pre-change baseline the
    commit_stream_overlap_seconds counter is measured against);
    gang_groups > 0 mixes that many PodGroups of gang_members pods into
    the queue with the Coscheduling plugin enabled, so the wave pays
    (and reports) the vectorized gang-quorum pass
    (docs/gang-scheduling.md)."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import make_nodes, make_pods
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    nodes = make_nodes(scale_nodes, seed=seed, taint_fraction=0.1)

    def _queue():
        pods = make_pods(scale_pods, seed=seed + 1, with_affinity=True,
                         with_tolerations=True, with_spread=True,
                         with_interpod=interpod)
        if gang_groups:
            from kube_scheduler_simulator_tpu.models.workloads import (
                make_gang_workload)

            pgs, gpods = make_gang_workload(gang_groups, gang_members,
                                            seed=seed + 4)
            return pods + gpods, pgs
        return pods, []

    pods, pgs = _queue()
    custom = {}
    enabled = [
        "NodeResourcesFit", "NodeResourcesBalancedAllocation", "NodeAffinity",
        "TaintToleration", "PodTopologySpread",
    ] + (["InterPodAffinity"] if interpod else [])
    store = ObjectStore()
    if gang_groups:
        from kube_scheduler_simulator_tpu.plugins.coscheduling import (
            Coscheduling, ensure_podgroup_resource)

        ensure_podgroup_resource(store)
        custom["Coscheduling"] = Coscheduling()
        enabled.append("Coscheduling")
    cfg = PluginSetConfig(enabled=enabled, custom=custom)
    for n in nodes:
        store.create("nodes", n)
    for pg in pgs:
        store.create("podgroups", pg)
    for p in pods:
        store.create("pods", p)
    engine = SchedulerEngine(store, plugin_config=cfg, chunk=512,
                             pipeline_commit=pipeline)
    log(f"engine path: {scale_pods} pods x {scale_nodes} nodes "
        "(store -> compile -> replay -> decode -> commit"
        f"{', pipelined' if pipeline else ', sequential post-pass'})")
    t0 = time.time()
    engine.schedule_pending()  # warm: XLA-compiles the wave's scan
    log(f"  warm engine wave (incl XLA compile): {time.time()-t0:.1f}s")
    # reset the pods (same statics fingerprint -> scan cache hit) and
    # measure the steady-state serving wave on fresh manifests
    for p in pods:
        meta = p["metadata"]
        store.delete("pods", meta["name"], meta.get("namespace"))
    fresh, _ = _queue()
    for p in fresh:
        store.create("pods", p)
    TRACER.reset()
    t0 = time.time()
    bound = engine.schedule_pending()
    total = time.time() - t0
    summary = TRACER.summary()
    spans = {k: v["total_seconds"] for k, v in summary["spans"].items()}
    for name, secs in sorted(spans.items(), key=lambda kv: -kv[1]):
        log(f"  span {name}: {secs:.2f}s")
    # the pipelined-commit win: commit time that ran DURING the replay
    # (docs/wave-pipeline.md) — plus the batched-write volume behind it
    counters = {
        k: summary["counters"][k] for k in (
            "commit_stream_overlap_seconds", "commit_stream_waves_total",
            "store_batch_writes_total", "store_batches_total",
            "replay_width_retries_total",
            "decode_chunk_calls_total", "decode_native_thread_seconds",
            "wave_attribution_seconds", "speculative_rounds_total",
            "wave_d2h_bytes_total", "d2h_on_demand_bytes_total",
            "device_chunks_spilled_total",
            "gang_groups_admitted_total", "gang_quorum_rollbacks_total",
            "gang_timeout_rejects_total", "gang_quorum_pass_seconds",
        ) if k in summary["counters"]
    }
    if counters.get("commit_stream_overlap_seconds"):
        log(f"  commit overlapped with replay: "
            f"{counters['commit_stream_overlap_seconds']:.2f}s")
    if counters.get("decode_chunk_calls_total"):
        log(f"  native chunk decode: "
            f"{counters['decode_chunk_calls_total']:.0f} calls, "
            f"{counters.get('decode_native_thread_seconds', 0.0):.2f}s of "
            f"C worker time")
    cps = scale_pods / total
    log(f"  engine: bound {bound}/{scale_pods} in {total:.2f}s -> {cps:,.0f} cycles/s")

    # lazy-decode headline (docs/wave-pipeline.md lazy-decode stage): how
    # much decode the wave DEFERRED, and what a consumer pays on first
    # read.  Cold = first GET of a pod (drains its deferred reflect +
    # decodes its whole chunk in one native call); warm = a chunk-mate
    # right after (memoized dict lookup + its own deferred write-back).
    lazy_reg = getattr(engine.reflector, "_lazy", None)
    deferred = lazy_reg.pending_count() if lazy_reg is not None else 0
    lazy_stats = {"deferred_pods": deferred,
                  "pods_materialized_in_wave": scale_pods - deferred}
    # device-residency headline (docs/wave-pipeline.md): how few bytes
    # the WAVE itself moved device->host (decision rows only in the
    # device-resident default), and what a cold read pays for the full
    # materialization (D2H + chunk decode + deferred reflect)
    if counters.get("wave_d2h_bytes_total") is not None:
        lazy_stats["wave_d2h_bytes"] = int(counters["wave_d2h_bytes_total"])
    if deferred:
        d2h0 = summary["counters"].get("d2h_on_demand_bytes_total", 0)
        sample = [p["metadata"] for p in pods[:2]]
        t0 = time.perf_counter()
        store.get("pods", sample[0]["name"], sample[0].get("namespace"))
        lazy_stats["cold_read_seconds"] = round(time.perf_counter() - t0, 6)
        lazy_stats["cold_read_d2h_bytes"] = int(
            TRACER.summary()["counters"].get("d2h_on_demand_bytes_total", 0)
            - d2h0)
        if len(sample) > 1:
            # second GET right after: pod 2 is pod 1's chunk-mate at
            # bench chunk sizes, so this is the memoized warm path
            t0 = time.perf_counter()
            store.get("pods", sample[1]["name"], sample[1].get("namespace"))
            lazy_stats["warm_read_seconds"] = round(
                time.perf_counter() - t0, 6)
        log(f"  lazy decode: {deferred}/{scale_pods} pods deferred past "
            f"the wave; wave D2H "
            f"{lazy_stats.get('wave_d2h_bytes', 0)/1e6:.1f}MB; first read "
            f"cold {lazy_stats['cold_read_seconds']*1e3:.1f}ms "
            f"({lazy_stats['cold_read_d2h_bytes']/1e6:.1f}MB materialized), "
            f"warm {lazy_stats.get('warm_read_seconds', 0)*1e3:.1f}ms")
    snap = TRACER.snapshot()
    return {"pods": scale_pods, "nodes": scale_nodes, "bound": bound,
            "cycles_per_sec": round(cps, 1),
            "lazy": lazy_stats,
            "spans": {k: round(v, 2) for k, v in spans.items()},
            "counters": {k: round(v, 3) for k, v in counters.items()},
            # the full flight-recorder snapshot (histograms + labeled
            # counters + per-plugin attribution, docs/metrics.md) rides
            # the BENCH artifact so perf rounds keep the whole surface
            "metrics": {"labeled_counters": snap["labeled_counters"],
                        "histograms": snap["histograms"]}}


def measure_gang(n_groups: int, members: int, scale_nodes: int, seed: int,
                 plain_pods: int = 0, park_groups: int = 0,
                 pipeline: bool = True):
    """Gang-workload serving benchmark (make bench-gang,
    docs/gang-scheduling.md): n_groups PodGroups of `members` pods
    (minMember == members, strict all-or-nothing) admitted through the
    vectorized quorum pass, optionally mixed with plain pods and
    `park_groups` below-quorum groups (one member made infeasible) that
    roll back to waiting.  Prints and returns the gang tracer counters
    so BENCH rounds can track gang throughput."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_gang_workload, make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        Coscheduling, ensure_podgroup_resource)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    def _build():
        store = ObjectStore()
        ensure_podgroup_resource(store)
        for n in make_nodes(scale_nodes, seed=seed):
            store.create("nodes", n)
        pgs, pods = make_gang_workload(n_groups, members, seed=seed + 1)
        if park_groups:
            ppgs, ppods = make_gang_workload(
                park_groups, members, seed=seed + 2, name_prefix="parked")
            for p in ppods:
                if p["metadata"]["name"].endswith("-member-000"):
                    # one infeasible member keeps the group below quorum
                    p["spec"]["containers"][0]["resources"]["requests"]["cpu"] \
                        = "9999999m"
            pgs += ppgs
            pods += ppods
        if plain_pods:
            pods += make_pods(plain_pods, seed=seed + 3)
        for pg in pgs:
            store.create("podgroups", pg)
        for p in pods:
            store.create("pods", p)
        cfg = PluginSetConfig(
            enabled=["NodeResourcesFit", "NodeResourcesBalancedAllocation",
                     "Coscheduling"],
            custom={"Coscheduling": Coscheduling()},
        )
        return pods, SchedulerEngine(store, plugin_config=cfg, chunk=512,
                                     pipeline_commit=pipeline)
    log(f"gang path: {n_groups} gangs x {members} members "
        f"(+{park_groups} below-quorum gangs, +{plain_pods} plain pods) "
        f"on {scale_nodes} nodes")
    _, warm = _build()
    t0 = time.time()
    warm.schedule_pending()  # warm: XLA-compiles the scan + quorum pass
    log(f"  warm gang wave (incl XLA compile): {time.time()-t0:.1f}s")
    warm.close()
    pods, engine = _build()
    TRACER.reset()
    t0 = time.time()
    bound = engine.schedule_pending()
    total = time.time() - t0
    summary = TRACER.summary()
    counters = {k: round(v, 6) for k, v in summary["counters"].items()
                if k.startswith("gang_")}
    for k, v in sorted(counters.items()):
        log(f"  {k}: {v}")
    pods_per_sec = len(pods) / total if total else 0.0
    log(f"  gang engine: bound {bound}/{len(pods)} in {total:.2f}s -> "
        f"{pods_per_sec:,.0f} pods/s ({len(engine.gang_parked)} parked)")
    snap = TRACER.snapshot()
    return {
        "metrics": {"labeled_counters": snap["labeled_counters"],
                    "histograms": snap["histograms"]},
        "groups": n_groups, "members": members, "nodes": scale_nodes,
        "park_groups": park_groups, "plain_pods": plain_pods,
        "bound": bound, "pods": len(pods), "parked": len(engine.gang_parked),
        "pods_per_sec": round(pods_per_sec, 1),
        "counters": counters,
    }


def _instrumented_compute_fraction(seq) -> float:
    """Fraction of a scheduling cycle spent in the per-node Filter/Score
    loops — the part upstream's 16-goroutine Parallelizer fans out.  Used
    to model a multi-core baseline when this host can't run one.  Run on
    a SHORT queue separate from the throughput measurement: the per-call
    timing wrappers inflate the total, so they must never touch the
    reported cycles/s figure."""
    acc = {"t": 0.0}

    def timed(fn):
        def wrap(*a, **kw):
            t0 = time.perf_counter()
            try:
                return fn(*a, **kw)
            finally:
                acc["t"] += time.perf_counter() - t0
        return wrap

    seq._filter = timed(seq._filter)
    seq._score = timed(seq._score)
    t0 = time.perf_counter()
    seq.schedule_all()
    total = time.perf_counter() - t0
    return min(acc["t"] / total, 0.99)


def _cpu_subprocess_json(snippet: str, prefix: str, timeout: float,
                         tag: str, relay_stderr: bool = False):
    """Run a CPU-forced bench snippet in a fresh subprocess and parse the
    one `<prefix> <json>` line it prints; None on failure (logged with
    the child's stderr tail, not the code string).  Shared by the
    under-cliff control and the engine-wave phase; wrapped in the
    host-phase ticker so a slow child cannot trip the hang watchdog."""
    import os as _os
    import subprocess as _sp

    code = (
        "import json, sys; sys.path.insert(0, '.')\n"
        "from kube_scheduler_simulator_tpu.utils.platform import force_cpu, "
        "tune_host_allocator\n"
        "force_cpu(); tune_host_allocator()\n"
        "import bench\n"
        + snippet
    )
    with _host_phase_ticker():
        try:
            r = _sp.run([sys.executable, "-c", code], timeout=timeout,
                        capture_output=True, text=True,
                        env={**_os.environ, "JAX_PLATFORMS": "cpu"},
                        cwd=str(Path(__file__).parent))
            if relay_stderr:
                for ln in r.stderr.splitlines():
                    log("  " + ln)
            return next(json.loads(ln[len(prefix) + 1:])
                        for ln in r.stdout.splitlines()
                        if ln.startswith(prefix + " "))
        except _sp.TimeoutExpired as e:
            err = (e.stderr or b"")
            err = err.decode(errors="replace") if isinstance(err, bytes) else err
            log(f"  {tag} subprocess timed out after {timeout:.0f}s; "
                f"stderr tail: {err.strip()[-300:]}")
        except StopIteration:
            log(f"  {tag} subprocess produced no result (rc={r.returncode}); "
                f"stderr tail: {r.stderr.strip()[-300:]}")
        return None


def _engine_wave_subprocess(pods: int, nodes: int, seed: int):
    """measure_engine in a fresh CPU-forced subprocess (see call site)."""
    return _cpu_subprocess_json(
        f"r = bench.measure_engine({pods}, {nodes}, {seed})\n"
        "print('EW ' + json.dumps(r))\n",
        "EW", 1200, "engine_10k_5k", relay_stderr=True)


def measure_serve(k_sessions: int, scale_pods: int, scale_nodes: int,
                  seed: int):
    """Multi-session serving benchmark (`make bench-serve`,
    docs/api.md sessions surface): K isolated SimulationSessions on one
    device, all at the SAME workload shape, scheduling concurrently.
    Reports aggregate cycles/s (total pods / wall), per-session and p99
    (slowest-session) cycles/s for a cold round (the first wave — one
    session pays the XLA compile, the rest reuse the process-level scan
    registry) and a warm round, plus the compile-cache hit rate the
    cross-session registry achieved (>= (K-1)/K for same-shape
    sessions: each distinct scan key compiles ONCE)."""
    import copy
    import threading

    import numpy as np

    from kube_scheduler_simulator_tpu.framework.replay import (
        scan_cache_stats)
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    enabled = [
        "NodeResourcesFit", "NodeResourcesBalancedAllocation", "NodeAffinity",
        "TaintToleration", "PodTopologySpread",
    ]
    log(f"serve path: {k_sessions} concurrent sessions x "
        f"({scale_pods} pods x {scale_nodes} nodes), shared compile cache")
    mgr = SessionManager(max_sessions=k_sessions + 1, idle_ttl=0,
                         start_scheduler=False)
    nodes = make_nodes(scale_nodes, seed=seed, taint_fraction=0.1)

    def fresh_pods():
        return make_pods(scale_pods, seed=seed + 1, with_affinity=True,
                         with_tolerations=True, with_spread=True)

    sessions = []
    for i in range(k_sessions):
        sess = mgr.create(f"bench-{i}")
        sess.di.engine.set_profiles(None)
        sess.di.engine.plugin_config = PluginSetConfig(enabled=list(enabled))
        for n in nodes:
            sess.di.store.create("nodes", copy.deepcopy(n))
        sessions.append(sess)
    cache0 = scan_cache_stats()
    TRACER.reset()

    def round_(tag: str) -> dict:
        for sess in sessions:
            for p in fresh_pods():
                sess.di.store.create("pods", p)
        barrier = threading.Barrier(k_sessions)
        walls = [0.0] * k_sessions
        bound = [0] * k_sessions
        errs: list = []

        def run(i: int):
            try:
                barrier.wait()
                t0 = time.perf_counter()
                bound[i] = sessions[i].di.engine.schedule_pending()
                walls[i] = time.perf_counter() - t0
            except Exception as e:  # surfaced below — a failed session
                errs.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(k_sessions)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise RuntimeError(f"serve round {tag}: {errs[0]}")
        per_session = [round(scale_pods / w, 1) for w in walls]
        agg = round(k_sessions * scale_pods / wall, 1)
        p99 = round(float(np.percentile(per_session, 1)), 1)
        log(f"  {tag}: aggregate {agg:,.0f} cycles/s, per-session "
            f"{sorted(per_session)} (p99 {p99:,.0f}), wall {wall:.2f}s, "
            f"bound {sum(bound)}/{k_sessions * scale_pods}")
        # drop each session's scheduled pods so the next round re-creates
        # the identical queue (same statics fingerprint -> cache hits)
        for sess in sessions:
            for p in sess.di.store.list("pods", copy_objects=False)[0][:]:
                meta = p["metadata"]
                sess.di.store.delete("pods", meta["name"],
                                     meta.get("namespace"))
        return {"aggregate_cycles_per_sec": agg,
                "p99_session_cycles_per_sec": p99,
                "per_session_cycles_per_sec": sorted(per_session),
                "wall_seconds": round(wall, 3),
                "bound": sum(bound)}

    cold = round_("cold (one shared compile)")
    warm = round_("warm (steady state)")
    cache1 = scan_cache_stats()
    hits = cache1["hits"] - cache0["hits"]
    misses = cache1["misses"] - cache0["misses"]
    hit_rate = round(hits / max(hits + misses, 1), 4)
    log(f"  compile cache: {hits} hits / {misses} misses "
        f"(rate {hit_rate:.2%}, floor {(k_sessions - 1) / k_sessions:.2%} "
        f"for same-shape sessions)")
    snap = TRACER.snapshot()
    # per-session speculative commit rate (docs/metrics.md): the measured
    # baseline cross-session wave batching starts from
    from kube_scheduler_simulator_tpu.server.sessions import (
        speculative_commit_rates)

    spec = speculative_commit_rates(TRACER)
    if spec:
        rates = {s: d["acceptRate"] for s, d in spec.items()}
        log(f"  speculative accept rate per session: {rates}")
    mgr.shutdown()
    return {"sessions": k_sessions, "pods": scale_pods, "nodes": scale_nodes,
            "cold": cold, "warm": warm,
            "compile_cache": {"hits": hits, "misses": misses,
                              "hit_rate": hit_rate,
                              "floor": round((k_sessions - 1) / k_sessions,
                                             4)},
            "speculative": spec,
            "metrics": {"labeled_counters": snap["labeled_counters"]}}


def measure_speculative(scale_pods: int, scale_nodes: int, seed: int,
                        reps: int = 3):
    """`make bench-spec`: same-process interleaved A/B of the DEFAULT
    speculative wave against the sequential scan (KSS_TPU_SPECULATIVE=0)
    at the engine shape, on two scenarios:

      * low_contention — the reserved-slot DL fleet
        (models/workloads.make_slot_pinned_workload): sparse, mostly
        disjoint feasibility, the shape where speculation turns P scan
        steps into ~ceil(P/B) batched rounds.  This is the headline A/B
        the >=1.5x acceptance bar measures.
      * contended — the standard broad-feasibility engine workload
        (every pod fits thousands of nodes), where byte-exact
        acceptance collapses and the contention controller must hand
        the wave to the scan fallback at ~scan cost.

    Reports best-of-`reps` cycles/s per arm (arms alternate within one
    process so host noise hits both), plus accept rate / rounds /
    fallbacks from the flight recorder — the keys bench_check gates."""
    import os

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods, make_slot_pinned_workload)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    def scenario(name: str, nodes: list, pods: list, enabled: list) -> dict:
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        engine = SchedulerEngine(store,
                                 plugin_config=PluginSetConfig(
                                     enabled=list(enabled)), chunk=512)
        log(f"speculative A/B [{name}]: {len(pods)} pods x {len(nodes)} "
            f"nodes, {reps} reps/arm interleaved")

        def wave(spec_on: bool) -> tuple[float, int]:
            for p in pods:
                store.create("pods", p)
            prev = os.environ.get("KSS_TPU_SPECULATIVE")
            os.environ["KSS_TPU_SPECULATIVE"] = "1" if spec_on else "0"
            try:
                t0 = time.perf_counter()
                bound = engine.schedule_pending()
                wall = time.perf_counter() - t0
            finally:
                if prev is None:
                    os.environ.pop("KSS_TPU_SPECULATIVE", None)
                else:
                    os.environ["KSS_TPU_SPECULATIVE"] = prev
            for p in store.list("pods", copy_objects=False)[0][:]:
                meta = p["metadata"]
                store.delete("pods", meta["name"], meta.get("namespace"))
            return wall, bound

        # one warm wave per arm: XLA compiles (spec rungs + oracle +
        # commit on one side, the chunked scan on the other) stay out of
        # the measured reps
        wave(True)
        wave(False)
        spec_walls, seq_walls = [], []
        bound = 0
        spec_counters: dict = {}
        for r in range(reps):
            TRACER.reset()
            w, bound = wave(True)
            spec_walls.append(w)
            if r == 0:
                summary = TRACER.summary()["counters"]
                acc = TRACER.labeled_totals(
                    "speculative_accepted_total", "session").get("", 0)
                roll = TRACER.labeled_totals(
                    "speculative_rolled_back_total", "session").get("", 0)
                spec_counters = {
                    "rounds": int(summary.get("speculative_rounds_total", 0)),
                    "accepted": int(acc),
                    "rolled_back": int(roll),
                    "accept_rate": round(acc / (acc + roll), 4)
                        if acc + roll else None,
                    "fallbacks": int(sum(TRACER.labeled_totals(
                        "speculative_fallbacks_total", "session").values())),
                }
            w, _ = wave(False)
            seq_walls.append(w)
        spec_cps = round(scale_pods / min(spec_walls), 1)
        seq_cps = round(scale_pods / min(seq_walls), 1)
        fig = {
            "speculative_cycles_per_sec": spec_cps,
            "sequential_cycles_per_sec": seq_cps,
            "speedup": round(spec_cps / seq_cps, 3) if seq_cps else None,
            "bound": bound,
            **spec_counters,
        }
        engine.close()
        log(f"  [{name}] speculative {spec_cps:,.0f} vs sequential "
            f"{seq_cps:,.0f} cycles/s ({fig['speedup']}x), accept rate "
            f"{fig.get('accept_rate')}, {fig.get('rounds')} rounds, "
            f"{fig.get('fallbacks')} fallback(s)")
        return fig

    slot_nodes, slot_pods = make_slot_pinned_workload(
        scale_pods, scale_nodes, seed=seed)
    low = scenario("low_contention", slot_nodes, slot_pods,
                   ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
                    "NodeAffinity"])
    broad_nodes = make_nodes(scale_nodes, seed=seed, taint_fraction=0.1)
    broad_pods = make_pods(scale_pods, seed=seed + 1, with_affinity=True,
                           with_tolerations=True, with_spread=True)
    contended = scenario("contended", broad_nodes, broad_pods,
                         ["NodeResourcesFit",
                          "NodeResourcesBalancedAllocation", "NodeAffinity",
                          "TaintToleration", "PodTopologySpread"])
    return {"pods": scale_pods, "nodes": scale_nodes,
            "low_contention": low, "contended": contended}


def measure_fuse(k_sessions: int, scale_pods: int, scale_nodes: int,
                 seed: int, reps: int = 2, window_ms: int = 200):
    """`make bench-fuse`: cross-session fused dispatch A/B
    (parallel/fuse.py).  K sessions over the SAME reserved-slot fleet
    shape schedule concurrently twice — once with fusion on
    (KSS_TPU_FUSE=1, a generous straggler window so batch-mates
    reliably meet) and once time-shared (KSS_TPU_FUSE=0) — arms
    interleaved in one process so host noise hits both.  Reports
    best-of-`reps` aggregate and p99 per-session cycles/s per arm, the
    coordinator's dispatch tallies, and asserts the parity bar IN THE
    SAME RUN: every session's bound state (nodeName + annotations per
    pod) byte-identical across arms."""
    import copy
    import os
    import threading

    import numpy as np

    from kube_scheduler_simulator_tpu.models.workloads import (
        make_slot_pinned_workload)
    from kube_scheduler_simulator_tpu.parallel.fuse import FUSE
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager

    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
               "NodeAffinity"]
    nodes, pods = make_slot_pinned_workload(scale_pods, scale_nodes,
                                            seed=seed)
    log(f"fuse A/B: {k_sessions} sessions x ({scale_pods} pods x "
        f"{scale_nodes} nodes slot-pinned), fused vs time-shared")
    mgr = SessionManager(max_sessions=k_sessions + 1, idle_ttl=0,
                         start_scheduler=False)
    sessions = []
    for i in range(k_sessions):
        sess = mgr.create(f"fuse-{i}")
        sess.di.engine.set_profiles(None)
        sess.di.engine.plugin_config = PluginSetConfig(enabled=list(enabled))
        for n in nodes:
            sess.di.store.create("nodes", copy.deepcopy(n))
        sessions.append(sess)

    def wave(fuse_on: bool, capture: bool) -> tuple[float, list, list]:
        for sess in sessions:
            for p in pods:
                sess.di.store.create("pods", copy.deepcopy(p))
        prev = {k: os.environ.get(k)
                for k in ("KSS_TPU_FUSE", "KSS_TPU_FUSE_WINDOW_MS")}
        os.environ["KSS_TPU_FUSE"] = "1" if fuse_on else "0"
        os.environ["KSS_TPU_FUSE_WINDOW_MS"] = str(window_ms)
        barrier = threading.Barrier(k_sessions)
        walls = [0.0] * k_sessions
        errs: list = []

        def run(i: int):
            try:
                barrier.wait()
                t0 = time.perf_counter()
                sessions[i].di.engine.schedule_pending()
                walls[i] = time.perf_counter() - t0
            except Exception as e:
                errs.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(k_sessions)]
        try:
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if errs:
            raise RuntimeError(f"fuse wave ({fuse_on=}): {errs[0]}")
        states = []
        for sess in sessions:
            state = None
            if capture:
                state = {}
                for p in sess.di.store.list("pods", copy_objects=False)[0]:
                    meta = p["metadata"]
                    state[meta["name"]] = (
                        (p.get("spec") or {}).get("nodeName"),
                        tuple(sorted((meta.get("annotations")
                                      or {}).items())))
            states.append(state)
            for p in sess.di.store.list("pods", copy_objects=False)[0][:]:
                meta = p["metadata"]
                sess.di.store.delete("pods", meta["name"],
                                     meta.get("namespace"))
        return wall, walls, states

    # one warm wave per arm: XLA compiles (the solo rungs, then the
    # fused K-stacked executables) stay out of the measured reps
    wave(True, capture=False)
    wave(False, capture=False)
    stats0 = FUSE.stats()
    fused_states = solo_states = None
    fused_aggs, fused_p99s, solo_aggs, solo_p99s = [], [], [], []
    for r in range(reps):
        capture = r == 0
        wall, walls, st = wave(True, capture=capture)
        if capture:
            fused_states = st
        fused_aggs.append(k_sessions * scale_pods / wall)
        fused_p99s.append(float(np.percentile(
            [scale_pods / w for w in walls], 1)))
        wall, walls, st = wave(False, capture=capture)
        if capture:
            solo_states = st
        solo_aggs.append(k_sessions * scale_pods / wall)
        solo_p99s.append(float(np.percentile(
            [scale_pods / w for w in walls], 1)))
    stats1 = FUSE.stats()
    mgr.shutdown()
    fused_calls = stats1["fusedDeviceCalls"] - stats0["fusedDeviceCalls"]
    tally = {k: stats1["dispatches"].get(k, 0)
             - stats0["dispatches"].get(k, 0)
             for k in ("fused", "timeshared", "window_timeout")}
    # the parity bar, asserted in the same run as the measurement: a
    # fused wave that drifted a single annotation byte is a wrong
    # answer, not a fast one
    parity = fused_states == solo_states
    if not parity:
        raise AssertionError(
            "fused vs time-shared session state diverged — parity bar "
            "violated")
    if fused_calls < 1:
        log("  WARNING: no fused device call happened in the fused arm "
            "(window too short or rungs diverged)")
    fig = {
        "sessions": k_sessions, "pods": scale_pods, "nodes": scale_nodes,
        "window_ms": window_ms,
        "fuse_aggregate_cycles_per_sec": round(max(fused_aggs), 1),
        "fuse_p99_session_cycles_per_sec": round(max(fused_p99s), 1),
        "timeshared_aggregate_cycles_per_sec": round(max(solo_aggs), 1),
        "timeshared_p99_session_cycles_per_sec": round(max(solo_p99s), 1),
        "aggregate_speedup": round(max(fused_aggs) / max(solo_aggs), 3)
            if solo_aggs and max(solo_aggs) else None,
        "fused_device_calls": fused_calls,
        "dispatches": tally,
        "parity_byte_identical": parity,
    }
    log(f"  fused {fig['fuse_aggregate_cycles_per_sec']:,.0f} vs "
        f"time-shared {fig['timeshared_aggregate_cycles_per_sec']:,.0f} "
        f"aggregate cycles/s ({fig['aggregate_speedup']}x), p99 "
        f"{fig['fuse_p99_session_cycles_per_sec']:,.0f} vs "
        f"{fig['timeshared_p99_session_cycles_per_sec']:,.0f}, "
        f"{fused_calls} fused device calls, parity OK")
    return fig


def measure_blackbox(scale_pods: int, scale_nodes: int, seed: int,
                     reps: int = 3):
    """Wave black-box overhead A/B (docs/metrics.md post-mortem dumps):
    the always-on event ring must stay within noise — same-process
    interleaved best-of-`reps` engine waves with recording enabled vs
    disabled (the KSS_TPU_BLACKBOX=0 lever), plus a byte-identity check
    on the annotations both arms produce (the recorder must never touch
    the product).  Reports on/off cycles/s and the overhead ratio
    bench_check gates (>=0.98 = the <=2% acceptance bar, noise-bound)."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils import blackbox

    nodes = make_nodes(scale_nodes, seed=seed, taint_fraction=0.1)
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
               "NodeAffinity", "TaintToleration", "PodTopologySpread"]
    log(f"blackbox overhead A/B: {scale_pods} pods x {scale_nodes} nodes, "
        f"{reps} reps/arm interleaved")

    def run() -> tuple[float, dict]:
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        for p in make_pods(scale_pods, seed=seed + 1, with_affinity=True,
                           with_tolerations=True, with_spread=True):
            store.create("pods", p)
        engine = SchedulerEngine(
            store, plugin_config=PluginSetConfig(enabled=enabled), chunk=512)
        t0 = time.perf_counter()
        engine.schedule_pending()
        wall = time.perf_counter() - t0
        # annotations read OUTSIDE the timed window (materializes the
        # lazy handles) — the byte-identity evidence per arm
        state = {}
        for p in store.list("pods")[0]:
            meta = p.get("metadata") or {}
            state[meta.get("name", "")] = (
                (p.get("spec") or {}).get("nodeName"),
                dict(meta.get("annotations") or {}))
        engine.close()
        return wall, state

    prev = blackbox.enabled()
    best = {True: float("inf"), False: float("inf")}
    states: dict = {}
    try:
        blackbox.set_enabled(True)
        run()  # warm: XLA compile stays out of the measured reps
        for _ in range(reps):
            for arm in (True, False):
                blackbox.set_enabled(arm)
                wall, state = run()
                best[arm] = min(best[arm], wall)
                states[arm] = state
    finally:
        blackbox.set_enabled(prev)
    identical = states.get(True) == states.get(False)
    if not identical:
        raise RuntimeError(
            "blackbox A/B produced different annotations — the recorder "
            "must never touch the product")
    on_cps = round(scale_pods / best[True], 1)
    off_cps = round(scale_pods / best[False], 1)
    ratio = round(on_cps / off_cps, 4) if off_cps else None
    log(f"  blackbox on {on_cps:,.0f} vs off {off_cps:,.0f} cycles/s "
        f"(ratio {ratio}); annotations byte-identical: {identical}")
    return {
        "pods": scale_pods, "nodes": scale_nodes,
        "on_cycles_per_sec": on_cps,
        "off_cycles_per_sec": off_cps,
        "overhead_ratio": ratio,
        "within_noise": ratio is not None and ratio >= 0.98,
        "annotations_identical": identical,
    }


def measure_history(scale_pods: int, scale_nodes: int, seed: int,
                    reps: int = 3):
    """Telemetry-history + trace-correlation overhead A/B
    (docs/metrics.md "History & correlation"): the always-on plane —
    columnar ring sampling (utils/history.py) and trace-id scope
    propagation — must cost <= 1.05x.  Same-process interleaved
    best-of-`reps` engine waves: the ON arm runs each wave under an
    explicit trace scope and takes a feeder sample per wave (the
    sampler thread's cadence, compressed); the OFF arm is the
    KSS_TPU_HISTORY=0 lever with no trace scope.  Annotations are
    asserted byte-identical across arms — the plane must never touch
    the product."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils import history
    from kube_scheduler_simulator_tpu.utils.blackbox import FEEDER
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    nodes = make_nodes(scale_nodes, seed=seed, taint_fraction=0.1)
    enabled = ["NodeResourcesFit", "NodeResourcesBalancedAllocation",
               "NodeAffinity", "TaintToleration", "PodTopologySpread"]
    log(f"history overhead A/B: {scale_pods} pods x {scale_nodes} nodes, "
        f"{reps} reps/arm interleaved")

    def run(arm: bool) -> tuple[float, dict]:
        store = ObjectStore()
        for n in nodes:
            store.create("nodes", n)
        for p in make_pods(scale_pods, seed=seed + 1, with_affinity=True,
                           with_tolerations=True, with_spread=True):
            store.create("pods", p)
        engine = SchedulerEngine(
            store, plugin_config=PluginSetConfig(enabled=enabled), chunk=512)
        trace = "bench-trace" if arm else None
        t0 = time.perf_counter()
        with TRACER.trace_scope(trace):
            engine.schedule_pending()
        FEEDER.sample()   # the sampler tick (no-op shape when off)
        wall = time.perf_counter() - t0
        state = {}
        for p in store.list("pods")[0]:
            meta = p.get("metadata") or {}
            state[meta.get("name", "")] = (
                (p.get("spec") or {}).get("nodeName"),
                dict(meta.get("annotations") or {}))
        engine.close()
        return wall, state

    prev = history.enabled()
    best = {True: float("inf"), False: float("inf")}
    states: dict = {}
    try:
        history.set_enabled(True)
        run(True)  # warm: XLA compile stays out of the measured reps
        for _ in range(reps):
            for arm in (True, False):
                history.set_enabled(arm)
                wall, state = run(arm)
                best[arm] = min(best[arm], wall)
                states[arm] = state
    finally:
        history.set_enabled(prev)
    identical = states.get(True) == states.get(False)
    if not identical:
        raise RuntimeError(
            "history A/B produced different annotations — the telemetry "
            "plane must never touch the product")
    on_cps = round(scale_pods / best[True], 1)
    off_cps = round(scale_pods / best[False], 1)
    ratio = round(on_cps / off_cps, 4) if off_cps else None
    log(f"  history on {on_cps:,.0f} vs off {off_cps:,.0f} cycles/s "
        f"(ratio {ratio}); annotations byte-identical: {identical}")
    return {
        "pods": scale_pods, "nodes": scale_nodes,
        "on_cycles_per_sec": on_cps,
        "off_cycles_per_sec": off_cps,
        "overhead_ratio": ratio,
        # the <=1.05x acceptance bar: on/off >= 1/1.05 ~= 0.9524
        "within_bound": ratio is not None and ratio >= 0.95,
        "annotations_identical": identical,
    }


def measure_cpu_baseline(idx: int, cpu_scale: float, node_scale: float,
                         seed: int, parallelism: int, cache: dict, rev: str):
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.reference_impl.parallel import ParallelScheduler
    from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
    from kube_scheduler_simulator_tpu.utils.platform import effective_cpu_count

    # effective (affinity-masked) count, matching main()'s forkserver
    # warm-up gate: a 1-CPU container on an 8-core host must not construct
    # ParallelScheduler with a cold forkserver after JAX threads exist
    cores = effective_cpu_count()
    out = {"cores": cores}

    # instrumented sequential run: throughput + the Filter/Score compute
    # fraction (what the upstream Parallelizer fans out)
    # "2": warm-slice protocol (cold-start transients excluded) — older
    # cached values measured a different thing and must not be reused
    skey = f"seqfrac2-c{idx}-s{cpu_scale}-ns{node_scale}-seed{seed}-{rev}"
    if skey in cache:
        out["sequential_cps"], frac = cache[skey]
        out["compute_fraction"] = round(frac, 3)
        log(f"CPU sequential baseline (cached): {out['sequential_cps']:,.1f} "
            f"cycles/s (compute fraction {frac:.2f})")
    else:
        cn, cp, ccfg = baseline_config(idx, scale=cpu_scale, seed=seed,
                                       node_scale=node_scale)
        log(f"CPU sequential baseline: {len(cp)} pods x {len(cn)} nodes")
        # warm slice first (untimed): the first big run in a process pays
        # allocator/THP/startup transients — measured 6.5 cycles/s for the
        # cold run vs 8.4 for the same oracle warmed, which would
        # UNDERSTATE the divisor and flatter vs_baseline
        wn, wp, wcfg = baseline_config(idx, scale=min(cpu_scale, 0.01),
                                       seed=seed, node_scale=node_scale)
        SequentialScheduler(wn, wp, wcfg).schedule_all()
        t0 = time.time()
        SequentialScheduler(cn, cp, ccfg).schedule_all()
        s = time.time() - t0
        out["sequential_cps"] = len(cp) / s
        # compute fraction from a separate SHORT instrumented run (the
        # wrappers bias the measured total)
        fn, fp, fcfg = baseline_config(idx, scale=min(cpu_scale, 0.01),
                                       seed=seed, node_scale=node_scale)
        frac = _instrumented_compute_fraction(SequentialScheduler(fn, fp, fcfg))
        cache[skey] = [out["sequential_cps"], frac]
        log(f"  {s:.2f}s -> {out['sequential_cps']:,.1f} cycles/s; "
            f"Filter/Score compute fraction {frac:.2f} "
            f"(pod queue at {cpu_scale}x, nodes at {node_scale}x; a shorter "
            "queue FAVORS the CPU — later pods see more bound pods)")
        out["compute_fraction"] = round(frac, 3)
    # queue-length bias: the divisor is measured on a short queue (0.05x);
    # quantify once how per-cycle cost shifts with a 4x longer queue so
    # the "is the short-queue divisor fair?" question has a number.
    # ratio > 1 means the short queue FAVORS the CPU (vs_baseline is
    # conservative); keyed without the git rev — it is a property of the
    # workload generator + oracle semantics, both frozen by parity gates
    bkey = f"qbias2-c{idx}-s{cpu_scale}-x4-ns{node_scale}-seed{seed}"
    if bkey in cache:
        out["queue_bias_ratio"] = cache[bkey]
        log(f"CPU queue-length bias (cached): {cache[bkey]:.3f}")
    else:
        bn, bp, bcfg = baseline_config(idx, scale=cpu_scale * 4, seed=seed,
                                       node_scale=node_scale)
        t0 = time.time()
        SequentialScheduler(bn, bp, bcfg).schedule_all()
        long_cps = len(bp) / (time.time() - t0)
        out["queue_bias_ratio"] = round(out["sequential_cps"] / long_cps, 3)
        cache[bkey] = out["queue_bias_ratio"]
        log(f"CPU queue-length bias: sequential at {cpu_scale*4}x queue = "
            f"{long_cps:,.1f} cycles/s -> short-queue bias ratio "
            f"{out['queue_bias_ratio']:.3f} (>1: the short-queue divisor "
            "FAVORS the CPU, vs_baseline is conservative)")

    # modeled 16-way baseline (upstream Parallelizer): Amdahl over the
    # measured compute fraction — the honest divisor when this host lacks
    # the cores to run the fan-out for real
    modeled = out["sequential_cps"] / ((1 - frac) + frac / parallelism)
    out["parallel_modeled_cps"] = modeled
    log(f"CPU parallel-{parallelism} baseline (MODELED from compute fraction; "
        f"this host has {cores} core{'s' if cores != 1 else ''}): "
        f"{modeled:,.1f} cycles/s")
    if cores > 1:
        pkey = f"par{parallelism}-c{idx}-s{cpu_scale}-ns{node_scale}-seed{seed}-{rev}"
        if pkey in cache:
            out["parallel_cps"] = cache[pkey]
            log(f"CPU parallel-{parallelism} baseline (cached): "
                f"{cache[pkey]:,.1f} cycles/s")
        else:
            cn, cp, ccfg = baseline_config(idx, scale=cpu_scale, seed=seed,
                                           node_scale=node_scale)
            # construct (spawns + handshakes the forkserver workers)
            # OUTSIDE the timed region: upstream's 16 goroutines pre-exist
            # in the scheduler process, and the old fork start method was
            # near-free COW — timing worker startup would silently
            # understate the divisor
            ps = ParallelScheduler(cn, cp, ccfg, parallelism=parallelism)
            t0 = time.time()
            ps.schedule_all()
            s = time.time() - t0
            out["parallel_cps"] = len(cp) / s
            cache[pkey] = out["parallel_cps"]
            log(f"CPU parallel-{parallelism} measured: {s:.2f}s -> "
                f"{out['parallel_cps']:,.1f} cycles/s")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=4, choices=[1, 2, 3, 4, 5])
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--gate-scale", type=float, default=0.05)
    ap.add_argument("--gate-configs", type=str, default="1,2,3,4,5")
    ap.add_argument("--cpu-scale", type=float, default=0.05,
                    help="pod-queue fraction for the CPU baseline run")
    ap.add_argument("--cpu-node-scale", type=float, default=1.0,
                    help="node-axis fraction for the CPU baseline; 1.0 "
                         "keeps the REAL cluster size so per-cycle cost is honest")
    ap.add_argument("--cpu-parallelism", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--unroll", type=int, default=2,
                    help="lax.scan unroll for the replay measurements "
                         "(the step's [N] ops are tiny, so per-iteration "
                         "overhead matters; 2 measured ~8%% faster than 1 "
                         "on the CPU backend, flat beyond)")
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard the node axis over this many devices "
                         "(0: unsharded single-chip)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true", help="tiny sizes, fast")
    ap.add_argument("--gang", action="store_true",
                    help="run ONLY the gang-workload bench shape "
                         "(make bench-gang) and print its counters")
    ap.add_argument("--serve", action="store_true",
                    help="run ONLY the multi-session serving shape "
                         "(make bench-serve): K concurrent sessions, "
                         "aggregate + p99 cycles/s, compile-cache hit rate")
    ap.add_argument("--serve-sessions", type=int, default=4)
    ap.add_argument("--spec", action="store_true",
                    help="run ONLY the speculative-wave A/B shape "
                         "(make bench-spec): default speculative wave vs "
                         "KSS_TPU_SPECULATIVE=0 sequential scan, "
                         "low-contention + contention-heavy scenarios")
    ap.add_argument("--fuse", action="store_true",
                    help="run ONLY the cross-session fused-dispatch A/B "
                         "(make bench-fuse): K sessions fused "
                         "(KSS_TPU_FUSE=1) vs time-shared (=0), aggregate "
                         "+ p99 cycles/s as K scales, parity asserted")
    ap.add_argument("--skip-parity", action="store_true")
    ap.add_argument("--skip-config5", action="store_true")
    ap.add_argument("--skip-engine", action="store_true")
    ap.add_argument("--assume-fallback", action="store_true",
                    help=argparse.SUPPRESS)  # set by the crash re-exec
    args = ap.parse_args()
    if args.serve:
        # standalone multi-session shape (make bench-serve): K isolated
        # sessions on one device — no THP/forkserver machinery needed,
        # each session's workload is far under the page cliff
        fig = (measure_serve(max(args.serve_sessions, 2), 60, 30, args.seed)
               if args.smoke else
               measure_serve(max(args.serve_sessions, 4), 600, 300,
                             args.seed))
        print(json.dumps({"metric": "serve_bench",
                          "value": fig["warm"]["aggregate_cycles_per_sec"],
                          "unit": "cycles/s", "extra": {"serve": fig}}))
        return
    if args.spec:
        # standalone speculative A/B (make bench-spec): lazy waves never
        # materialize the 13 GB annotation product, so no THP machinery
        fig = (measure_speculative(200, 100, args.seed, reps=1)
               if args.smoke else
               measure_speculative(max(int(10000 * args.scale), 100),
                                   max(int(5000 * args.scale), 50),
                                   args.seed))
        print(json.dumps({
            "metric": "speculative_bench",
            "value": fig["low_contention"]["speculative_cycles_per_sec"],
            "unit": "cycles/s", "extra": {"speculative": fig}}))
        return
    if args.fuse:
        # standalone fused-dispatch A/B (make bench-fuse): session
        # workloads are far under the page cliff, no THP machinery
        if args.smoke:
            ks, fig = [2], {2: measure_fuse(2, 60, 30, args.seed, reps=1)}
        else:
            ks = [2, 4, 8]
            fig = {k: measure_fuse(k, 600, 300, args.seed) for k in ks}
        headline = fig[4 if 4 in fig else ks[0]]
        extra = {f"k{k}": fig[k] for k in ks}
        if not args.smoke:
            # the big-fleet point: K=2 at the 10k x 5k slot-pinned
            # shape, one rep (compile-dominated past that); skip-safe so
            # a memory-starved host still ships the 600x300 sweep
            try:
                extra["k2_10k"] = measure_fuse(2, 10000, 5000, args.seed,
                                               reps=1)
            except Exception as e:  # noqa: BLE001 — reported, not fatal
                extra["k2_10k"] = {
                    "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps({
            "metric": "fuse_bench",
            "value": headline["fuse_aggregate_cycles_per_sec"],
            "unit": "cycles/s",
            "extra": {"fuse": extra}}))
        return
    if args.gang:
        # standalone gang shape (make bench-gang): no THP/forkserver
        # machinery needed — the workload is far under the page cliff
        fig = (measure_gang(8, 4, 32, args.seed, plain_pods=20,
                            park_groups=2) if args.smoke else
               measure_gang(100, 8, 500, args.seed, plain_pods=400,
                            park_groups=10))
        print(json.dumps({"metric": "gang_bench",
                          "value": fig["pods_per_sec"],
                          "unit": "pods/s", "extra": fig}))
        return
    # THP for the malloc arenas (re-execs once, before anything heavy):
    # the annotation product is ~13 GB of live strings at full scale and
    # 4 KiB-page first-touch faults dominate past this host's ~8 GB
    # page-backing cliff; measured 450 -> 575 engine cycles/s
    from kube_scheduler_simulator_tpu.utils.platform import (
        ensure_malloc_hugepages)

    ensure_malloc_hugepages()
    # the measured multi-core divisor's parallel-oracle workers must not
    # fork from this process once JAX threads exist (deadlock hazard);
    # start their forkserver NOW, while we are still single-threaded.
    # Only multi-core hosts ever construct a ParallelScheduler (the
    # parity gate streams the sequential oracle from a subprocess).
    from kube_scheduler_simulator_tpu.utils.platform import (
        effective_cpu_count)

    if effective_cpu_count() > 1:
        from kube_scheduler_simulator_tpu.reference_impl.parallel import (
            warm_forkserver)

        warm_forkserver()
    import os as _os_main

    if (_os_main.environ.get("KSS_BENCH_NO_REEXEC") != "1"
            and not args.assume_fallback):
        _start_hang_watchdog(args)
    try:
        _run(args)
    except SystemExit:
        raise
    except BaseException as e:
        # the accelerator tunnel can die MID-RUN (UNAVAILABLE on a
        # device_put after the gates already passed); the jax backend
        # cannot be re-initialized in-process, so re-exec a reduced-scale
        # CPU fallback — one JSON line must always come out
        import os as _os
        import subprocess as _sp

        if _os.environ.get("KSS_BENCH_NO_REEXEC") == "1":
            raise
        _claim_stdout_or_park("crash")
        log(f"WARNING: bench crashed mid-run ({type(e).__name__}: {e}); "
            "re-running on the CPU backend in a fresh process (full replay "
            "shape, honest full-node divisor; big engine phases skipped "
            "for time safety)")
        env = {**_os.environ, "JAX_PLATFORMS": "cpu",
               "KSS_BENCH_NO_REEXEC": "1"}
        # full workload + divisor shape: the CPU-XLA columnar program holds
        # ~1,500 warm cycles/s at 10k x 5k (measured, BASELINE.md), so the
        # whole re-exec stays under ~10 min; --assume-fallback keeps the
        # expensive extras (full-scale engine waves, under-cliff control)
        # out.  One gate config (the requested one) bounds the gate cost;
        # the user's shape/skip flags are forwarded so the fallback answers
        # the question the invocation asked.
        r = _sp.run(_fallback_cmd(args), env=env)
        raise SystemExit(r.returncode)


def _run(args):
    from kube_scheduler_simulator_tpu.utils.platform import tune_host_allocator

    tune_host_allocator()  # string churn must reuse pages, not re-fault them
    args.fallback = args.assume_fallback
    if args.smoke:
        args.scale, args.cpu_scale, args.chunk = 0.02, 0.02, 64
        args.cpu_node_scale, args.gate_scale = 0.02, 0.01
        args.gate_configs = "4"
        args.skip_config5 = True

    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from kube_scheduler_simulator_tpu.utils.platform import force_cpu

        force_cpu()
    elif not _device_initializes():
        # the axon relay can wedge (a killed client's chip claim lingers
        # and every jax.devices() call then hangs); never hang the
        # harness — fall back to the CPU XLA backend, flagged by the
        # _cpu_fallback metric suffix
        log("WARNING: TPU backend did not initialize within the probe "
            "timeout; falling back to the CPU XLA backend")
        os.environ["JAX_PLATFORMS"] = "cpu"
        from kube_scheduler_simulator_tpu.utils.platform import force_cpu

        force_cpu()
        # the columnar program holds ~1,500 warm cycles/s at the FULL
        # 10k x 5k shape even on one CPU core (config 4; ~800 for
        # config 5 — whole bench incl. both full-scale runs: <4 min
        # measured), so the fallback keeps the real workload scale, the
        # honest full-node-axis divisor, and the config-5 run
        args.fallback = True

    import jax

    from kube_scheduler_simulator_tpu.models.workloads import BASELINE_CONFIGS

    log(f"devices: {jax.devices()}")

    # --- parity gate (all configs) --------------------------------------
    if not args.skip_parity:
        for idx in [int(x) for x in args.gate_configs.split(",") if x]:
            t0 = time.time()
            ok = run_parity_gate(idx, args.gate_scale, args.seed)
            log(f"parity gate (config {idx} @{args.gate_scale}): "
                f"{'OK' if ok else 'FAILED'} ({time.time()-t0:.1f}s)")
            if not ok:
                _claim_stdout_or_park("run")
                print(json.dumps({
                    "metric": f"scheduling_cycles_per_sec_config{idx}",
                    "value": 0.0, "unit": "cycles/s", "vs_baseline": 0.0,
                }))
                return

    # --- TPU measurements -----------------------------------------------
    main_fig = measure_replay(args.config, args.scale, args.seed, args.chunk,
                              args.mesh, unroll=args.unroll)
    extra = {"device_only_cps": main_fig["device_only_cps"],
             "incl_host_transfer_cps": main_fig["incl_host_transfer_cps"],
             "decode_pods_per_sec": main_fig["decode_pods_per_sec"]}

    if not args.skip_config5 and args.config != 5:
        # decode_sample on: config 5's decode rate (InterPodAffinity blobs
        # ride the same distinct-tuple codec) is a first-class figure —
        # round-4 verdict asked for decode_pods_per_sec at this config
        extra["config5"] = measure_replay(5, args.scale, args.seed, args.chunk,
                                          args.mesh, decode_sample=512,
                                          unroll=args.unroll)

    if args.scale >= 1.0 and not args.assume_fallback:
        # under-cliff control: this bench host's first-touch page backing
        # collapses ~10x beyond ~8 GB resident (committed curve:
        # docs/bench/r04-host-page-backing.json), which bounds the
        # FULL-shape annotations-materialized figure at ~220 pods/s no
        # matter how fast the decoder is.  A 0.4x queue at the full node
        # shape holds ~5 GB and shows the code's sustained rate without
        # the host artifact.  Runs in a FRESH SUBPROCESS (on the CPU
        # backend) so the parent's already-touched memory cannot distort
        # the control in either direction.
        log("under-cliff control (0.4x queue, full node shape, subprocess):")
        uc = _cpu_subprocess_json(
            f"uc = bench.measure_replay({args.config}, 0.4, {args.seed}, "
            f"{args.chunk}, 0, decode_sample=0, node_scale=1.0, quick=True, "
            f"unroll={args.unroll})\n"
            "print('UC ' + json.dumps(uc))\n",
            "UC", 900, "under-cliff control")
        if uc is not None:
            extra["decode_inclusive_cps_undercliff"] = uc["decode_inclusive_cps"]
            extra["undercliff_shape"] = {"pods": uc["pods"], "nodes": uc["nodes"]}
            log(f"  under-cliff: {uc['decode_inclusive_cps']} cycles/s "
                f"({uc['pods']} pods x {uc['nodes']} nodes)")
        else:
            extra["decode_inclusive_cps_undercliff"] = None

    if not args.skip_engine:
        ep, en = (1000, 500) if not args.smoke else (50, 25)
        extra["engine"] = measure_engine(ep, en, args.seed)
        if not args.smoke and not args.assume_fallback:
            # the post-crash minimal re-exec (--assume-fallback) must stay
            # cheap to guarantee its one JSON line; every other run — TPU
            # or wedge fallback — benchmarks the serving path at the full
            # config-4 shape (annotations + reflect included; the per-pod
            # result JSON lives in the store until the next reset, ~13 GB
            # at 10k x 5k).  The full-scale wave only runs when the HOST
            # can hold that product: a memory-starved TPU host must not
            # trade its headline artifact for a kernel OOM kill
            extra["engine_2k_1k"] = measure_engine(2000, 1000, args.seed)
            avail = _available_gb()
            if avail < 20:
                log(f"skipping engine_10k_5k: only {avail:.1f} GiB "
                    "available on this host (needs ~20 for the resident "
                    "result store)")
                extra["engine_10k_5k"] = None
            elif jax.default_backend() == "cpu":
                # fresh subprocess: the wave holds the full ~13 GB product
                # and THP allocation degrades late in a long process
                # (fragmentation) — in-process this phase measured 200-450
                # cycles/s vs 575 from a clean process.  A fresh process is
                # also the representative serving shape (a server boots,
                # then serves waves).  CPU backend only: a TPU subprocess
                # would contend with this process's chip claim.
                extra["engine_10k_5k"] = _engine_wave_subprocess(
                    max(int(10000 * args.scale), 100),
                    max(int(5000 * args.scale), 50), args.seed)
            else:
                extra["engine_10k_5k"] = measure_engine(
                    max(int(10000 * args.scale), 100),
                    max(int(5000 * args.scale), 50), args.seed)
            # the config-5 hard plugin on the serving path
            extra["engine_interpod"] = measure_engine(ep, en, args.seed,
                                                      interpod=True)

    # --- multi-session serving ------------------------------------------
    # the serve snapshot rides every committed BENCH round so bench-check
    # can gate the aggregate/p99/compile-cache-hit-rate trajectory
    # (union/skip semantics keep pre-session rounds green)
    if not args.assume_fallback:
        try:
            extra["serve"] = (measure_serve(2, 50, 25, args.seed)
                              if args.smoke else
                              measure_serve(4, 600, 300, args.seed))
        except Exception as e:  # never trade the headline for the serve tap
            log(f"serve phase failed: {type(e).__name__}: {e}")
            extra["serve"] = None

    # --- speculative wave A/B -------------------------------------------
    # rides every committed BENCH round so bench_check can gate the
    # speculative cycles/s + accept-rate trajectory at the 10k x 5k
    # shape (union/skip semantics keep pre-speculative rounds green)
    if not args.assume_fallback and not args.skip_engine:
        try:
            if args.smoke:
                extra["speculative"] = measure_speculative(
                    200, 100, args.seed, reps=1)
            elif _available_gb() < 10:
                log("skipping speculative A/B: low host memory")
                extra["speculative"] = None
            else:
                extra["speculative"] = measure_speculative(
                    max(int(10000 * args.scale), 100),
                    max(int(5000 * args.scale), 50), args.seed)
        except Exception as e:  # never trade the headline for this tap
            log(f"speculative phase failed: {type(e).__name__}: {e}")
            extra["speculative"] = None

    # --- cross-session fused dispatch A/B -------------------------------
    # rides every committed BENCH round so bench_check can gate the
    # fused aggregate/p99 trajectory at K=4 (union/skip semantics keep
    # pre-fuse rounds green); parity asserted inside the measurement
    if not args.assume_fallback:
        try:
            extra["fuse"] = (measure_fuse(2, 60, 30, args.seed, reps=1)
                             if args.smoke else
                             measure_fuse(4, 600, 300, args.seed))
        except Exception as e:  # never trade the headline for this tap
            log(f"fuse phase failed: {type(e).__name__}: {e}")
            extra["fuse"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    # --- wave black box -------------------------------------------------
    # overhead A/B (on vs KSS_TPU_BLACKBOX=0) + byte-identity assert
    # rides every committed round so bench_check can gate the ratio, and
    # the HBM sampler's snapshot records what the device plane saw
    if not args.assume_fallback:
        try:
            bp, bn = (60, 30) if args.smoke else (1000, 500)
            extra["blackbox"] = measure_blackbox(bp, bn, args.seed)
        except Exception as e:
            # record the FAILURE, not None: an annotation-divergence
            # raise must make bench_check refuse the round (the chaos
            # gate's own no-silently-vacuous principle), while still
            # never trading the headline line for this tap
            log(f"blackbox phase failed: {type(e).__name__}: {e}")
            extra["blackbox"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    # --- telemetry history + trace correlation --------------------------
    # overhead A/B (on vs KSS_TPU_HISTORY=0) + byte-identity assert,
    # same discipline as the blackbox tap above: bench_check gates the
    # history_overhead_ratio, and a divergence raise lands as an error
    # payload that refuses the round rather than a silent skip
    if not args.assume_fallback:
        try:
            hp, hn = (60, 30) if args.smoke else (1000, 500)
            extra["history"] = measure_history(hp, hn, args.seed)
        except Exception as e:
            log(f"history phase failed: {type(e).__name__}: {e}")
            extra["history"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    try:
        from kube_scheduler_simulator_tpu.utils.blackbox import TELEMETRY
        extra["hbm"] = TELEMETRY.sample_once()
    except Exception as e:
        extra["hbm"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # --- CPU baseline ---------------------------------------------------
    cache_path = Path(__file__).parent / ".bench_cpu_cache.json"
    cache = json.loads(cache_path.read_text()) if cache_path.exists() else {}
    try:
        import subprocess

        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=Path(__file__).parent,
        ).stdout.strip() or "norev"
    except OSError:
        rev = "norev"
    with _host_phase_ticker():
        # pure-host phase: the full-node-axis sequential divisor can run
        # for several minutes with no log lines on a slow TPU-VM core —
        # it cannot wedge on the tunnel, so keeping the watchdog fed is
        # safe (advisor round-4 finding)
        cpu = measure_cpu_baseline(
            args.config, args.cpu_scale, args.cpu_node_scale, args.seed,
            args.cpu_parallelism, cache, rev)
    try:
        cache_path.write_text(json.dumps(cache))
    except OSError:
        pass

    full = BASELINE_CONFIGS[args.config]
    shape = (f"{full['pods']}pods_{full['nodes']}nodes" if args.scale == 1.0
             else f"scale{args.scale}")
    # headline: the ANNOTATIONS-MATERIALIZED end-to-end figure — every
    # pod's result JSON decoded to its final string, the same per-pod
    # product the CPU oracle (and the reference's reflector) pays for
    metric = (f"scheduling_cycles_per_sec_e2e_annotations_config{args.config}"
              f"_{shape}")
    if args.fallback:
        metric += "_cpu_fallback"
        # the wedged-tunnel fallback is a same-host CPU run; point the
        # reader at the committed real-TPU evidence for the device rates
        extra["real_tpu_session_artifact"] = (
            "docs/bench/r04-tpu-session.log: parity gates configs 1-5 on "
            "the v5e-1; config 4 at 2,831 device cycles/s, config 5 at "
            "2,738 (predates the round-4 transfer/decode wins)")
    e2e = main_fig["decode_inclusive_cps"] or main_fig["incl_host_transfer_cps"]
    # divisor: the strongest CPU figure available — a measured multi-core
    # run when the host has cores, else the Amdahl-modeled 16-way number
    par_cps = max(cpu.get("parallel_cps", 0.0), cpu["parallel_modeled_cps"])
    extra.update({
        "cpu_parallel_modeled_cps": round(cpu["parallel_modeled_cps"], 1),
        "cpu_parallel_measured_cps": round(cpu["parallel_cps"], 1)
        if "parallel_cps" in cpu else None,
        "cpu_sequential_baseline_cps": round(cpu["sequential_cps"], 1),
        "cpu_compute_fraction": cpu.get("compute_fraction"),
        "cpu_cores_on_host": cpu["cores"],
        "cpu_parallelism": args.cpu_parallelism,
        "cpu_queue_bias_ratio": cpu.get("queue_bias_ratio"),
        "cpu_baseline_shape": {
            "pods": int(full["pods"] * args.cpu_scale),
            "nodes": int(full["nodes"] * args.cpu_node_scale),
        },
        "vs_baseline_device_only": round(main_fig["device_only_cps"] / par_cps, 1),
    })
    # record the kss-analyze verdict for the tree this round ran from:
    # bench-check refuses to compare a round produced with outstanding
    # analyzer findings (a hot-path pod-loop or a blocking-under-lock
    # hold skews exactly the metrics the gate protects)
    try:
        from tools.analysis import analysis_verdict
        extra["analysis"] = analysis_verdict()
    except Exception as e:  # never fail a bench run over the analyzer
        extra["analysis"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    # the chaos verdict rides every round too (docs/fault-injection.md):
    # one quick seeded fault-plan run proving waves still complete via
    # retry/degradation with bit-identical results — bench-check refuses
    # rounds whose chaos run failed
    try:
        from tools.chaos import chaos_verdict
        extra["chaos"] = chaos_verdict(seeds=1, quick=True)
    except Exception as e:  # never fail a bench run over the harness
        extra["chaos"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # claim stdout before emitting the one JSON line: if the hang
    # watchdog fired mid-run (a wedged device op that later RETURNED
    # instead of raising), its fallback child owns stdout — park until
    # its _os._exit ends this process rather than racing a second line
    _claim_stdout_or_park("run")
    print(json.dumps({
        "metric": metric,
        "value": e2e,
        "unit": "cycles/s",
        "vs_baseline": round(e2e / par_cps, 1),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
