#!/usr/bin/env python
"""Benchmark: scheduling-cycles/sec on the BASELINE configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol (BASELINE.md): replay a pod queue; a completed scheduling cycle =
a pod through Filter -> Score -> Normalize -> select -> bind (the
reference counts Reserve reached).  The TPU number is the warm steady-state
replay of the full config (default: config 4, 10k pods x 5k nodes) with
all per-plugin filter/score/finalscore result tensors materialised on
device; host transfer of the result tensors (the reference does annotation
write-back asynchronously in its reflector) is reported separately on
stderr.

The CPU baseline is this repo's sequential reference scheduler (same
semantics, scalar per-pod/per-node loops — the reference's execution
style) measured at --cpu-scale of the workload.  Per-cycle CPU cost GROWS
with node count and queue length, so the reduced-scale CPU cycles/sec
OVERESTIMATES full-scale CPU throughput, making vs_baseline conservative.
A small-scale bit-parity check of all annotations gates the result.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run_parity_gate(idx: int, seed: int) -> bool:
    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import baseline_config
    from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
    from kube_scheduler_simulator_tpu.state.compile import compile_workload
    from kube_scheduler_simulator_tpu.store.decode import decode_pod_result

    nodes, pods, cfg = baseline_config(idx, scale=0.01, seed=seed)
    seq = SequentialScheduler(nodes, pods, cfg).schedule_all()
    rr = replay(compile_workload(nodes, pods, cfg), chunk=64)
    for i, (sa, _) in enumerate(seq):
        da = decode_pod_result(rr, i)
        for k, v in sa.items():
            if da[k] != v:
                log(f"PARITY MISMATCH pod {i} key {k}\n  dev={da[k][:200]}\n  seq={v[:200]}")
                return False
    return True


def _device_initializes(timeout: float = 240) -> bool:
    """Probe device-backend init in a subprocess so a wedged accelerator
    tunnel cannot hang this process."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=4, choices=[1, 2, 3, 4, 5])
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--cpu-scale", type=float, default=0.05,
                    help="pod-queue fraction for the CPU baseline run")
    ap.add_argument("--cpu-node-scale", type=float, default=1.0,
                    help="node-axis fraction for the CPU baseline; 1.0 "
                         "keeps the REAL cluster size so per-cycle cost is "
                         "honest (per-cycle work grows with node count)")
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard the node axis over this many devices "
                         "(0: unsharded). Single-chip bench runs leave "
                         "this 0; the virtual-CPU mesh path is validated "
                         "by dryrun_multichip + tests/test_mesh.py")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true", help="tiny sizes, fast")
    ap.add_argument("--skip-parity", action="store_true")
    args = ap.parse_args()
    args.fallback = False
    if args.smoke:
        args.scale, args.cpu_scale, args.chunk = 0.02, 0.02, 64
        args.cpu_node_scale = 0.02

    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from kube_scheduler_simulator_tpu.utils.platform import force_cpu

        force_cpu()
    elif not _device_initializes():
        # the axon relay can wedge (a killed client's chip claim lingers
        # and every jax.devices() call then hangs); never hang the
        # harness — fall back to the CPU backend at reduced scale and
        # say so in the metric name
        log("WARNING: TPU backend did not initialize within the probe "
            "timeout; falling back to CPU backend at reduced scale")
        os.environ["JAX_PLATFORMS"] = "cpu"
        from kube_scheduler_simulator_tpu.utils.platform import force_cpu

        force_cpu()
        args.scale = min(args.scale, 0.05)
        args.cpu_node_scale = args.scale
        args.fallback = True

    import jax

    from kube_scheduler_simulator_tpu.framework.replay import replay
    from kube_scheduler_simulator_tpu.models.workloads import BASELINE_CONFIGS, baseline_config
    from kube_scheduler_simulator_tpu.reference_impl.sequential import SequentialScheduler
    from kube_scheduler_simulator_tpu.state.compile import compile_workload

    log(f"devices: {jax.devices()}")

    # --- parity gate ----------------------------------------------------
    if not args.skip_parity:
        t0 = time.time()
        ok = run_parity_gate(args.config, args.seed)
        log(f"parity gate (config {args.config} @0.01): {'OK' if ok else 'FAILED'} "
            f"({time.time()-t0:.1f}s)")
        if not ok:
            print(json.dumps({
                "metric": f"scheduling_cycles_per_sec_config{args.config}",
                "value": 0.0, "unit": "cycles/s", "vs_baseline": 0.0,
            }))
            return

    # --- TPU measurement ------------------------------------------------
    nodes, pods, cfg = baseline_config(args.config, scale=args.scale, seed=args.seed)
    log(f"TPU workload: {len(pods)} pods x {len(nodes)} nodes, plugins={cfg.enabled}")
    t0 = time.time()
    cw = compile_workload(nodes, pods, cfg)
    log(f"compile_workload (host precompile): {time.time()-t0:.1f}s")

    mesh = None
    if args.mesh:
        from kube_scheduler_simulator_tpu.parallel.mesh import make_mesh

        shards = args.mesh
        while shards > 1 and len(nodes) % shards:
            shards -= 1  # node axis must divide evenly across shards
        if shards > 1:
            mesh = make_mesh(shards, dp=1)
            log(f"mesh: node axis sharded over {shards} devices"
                + (f" (requested {args.mesh}, reduced to divide {len(nodes)} nodes)"
                   if shards != args.mesh else ""))
        else:
            log(f"mesh: {len(nodes)} nodes not divisible by any shard count "
                f"<= {args.mesh}; running unsharded")

    t0 = time.time()
    rr = replay(cw, chunk=args.chunk, collect=False, mesh=mesh)  # warm-up: XLA compile + run
    log(f"warm-up replay: {time.time()-t0:.1f}s, scheduled {rr.scheduled}/{len(pods)}")

    t0 = time.time()
    rr = replay(cw, chunk=args.chunk, collect=False, mesh=mesh)
    tpu_s = time.time() - t0
    tpu_cps = len(pods) / tpu_s
    log(f"timed replay (results on device): {tpu_s:.2f}s -> {tpu_cps:,.0f} cycles/s")

    t0 = time.time()
    replay(cw, chunk=args.chunk, collect=True, mesh=mesh)
    log(f"replay incl. host transfer of result tensors: {time.time()-t0:.2f}s "
        f"-> {len(pods)/(time.time()-t0):,.0f} cycles/s")

    # --- CPU baseline ---------------------------------------------------
    cache_path = Path(__file__).parent / ".bench_cpu_cache.json"
    cache = json.loads(cache_path.read_text()) if cache_path.exists() else {}
    # key includes the git revision so a code change invalidates the
    # cached baseline instead of silently skewing vs_baseline
    try:
        import subprocess

        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=Path(__file__).parent,
        ).stdout.strip() or "norev"
    except OSError:
        rev = "norev"
    key = f"c{args.config}-s{args.cpu_scale}-ns{args.cpu_node_scale}-seed{args.seed}-{rev}"
    if key in cache:
        cpu_cps = cache[key]
        log(f"CPU baseline (cached): {cpu_cps:,.1f} cycles/s")
    else:
        cn, cp, ccfg = baseline_config(args.config, scale=args.cpu_scale,
                                       seed=args.seed,
                                       node_scale=args.cpu_node_scale)
        log(f"CPU baseline workload: {len(cp)} pods x {len(cn)} nodes (sequential reference)")
        seq = SequentialScheduler(cn, cp, ccfg)
        t0 = time.time()
        seq.schedule_all()
        cpu_s = time.time() - t0
        cpu_cps = len(cp) / cpu_s
        log(f"CPU sequential: {cpu_s:.2f}s -> {cpu_cps:,.1f} cycles/s "
            f"(pod queue at {args.cpu_scale}x, nodes at {args.cpu_node_scale}x; "
            "a shorter queue slightly FAVORS the CPU baseline — later pods "
            "see more bound pods and cost more per cycle)")
        cache[key] = cpu_cps
        try:
            cache_path.write_text(json.dumps(cache))
        except OSError:
            pass

    full = BASELINE_CONFIGS[args.config]
    metric = (f"scheduling_cycles_per_sec_config{args.config}_{full['pods']}pods_{full['nodes']}nodes"
              if args.scale == 1.0 else
              f"scheduling_cycles_per_sec_config{args.config}_scale{args.scale}")
    if args.fallback:
        metric += "_cpu_fallback"
    print(json.dumps({
        "metric": metric,
        "value": round(tpu_cps, 1),
        "unit": "cycles/s",
        "vs_baseline": round(tpu_cps / cpu_cps, 1),
    }))


if __name__ == "__main__":
    main()
