"""Lock-witness: a runtime lockdep for the concurrency/soak suites.

`install()` replaces `threading.Lock/RLock/Condition` with instrumented
factories.  Every wrapped lock records, at each successful acquisition,
an ordering edge from every lock the acquiring thread already holds to
the acquired one — building the global acquisition-order graph across
ALL threads of the run.  `assert_no_cycles()` then fails on any cycle:
an A->B / B->A inversion is a potential deadlock even when this
particular interleaving never parked (exactly how the kernel's lockdep
reports deadlocks that "didn't happen"), and a same-thread reacquisition
of a non-reentrant Lock is the single-lock variant — the shape of the
PR 3 `kubeapi._rv_int` bug.

conftest.py installs the witness for the whole run when
`KSS_TPU_LOCK_WITNESS=1` and asserts no cycles after every test in the
concurrency/engine soak modules (docs/static-analysis.md).  The wrappers
are drop-in: `with`, acquire/release with blocking/timeout, Condition
wait/notify (wait's release-reacquire updates the held set through
`_release_save`/`_acquire_restore`), and `Event`/`queue.Queue` built on
the patched factories keep working — their internal locks are simply
witnessed too, widening coverage for free.

Lock identity is the creation site (file:line of the factory call), so a
report names code, not object ids.
"""

from __future__ import annotations

import threading
import traceback
import _thread

_REAL_LOCK = _thread.allocate_lock
_REAL_RLOCK = threading._CRLock or threading._PyRLock  # type: ignore[attr-defined]
_REAL_CONDITION = threading.Condition
_ORIG_FACTORIES = (threading.Lock, threading.RLock, threading.Condition)


class LockOrderViolation(AssertionError):
    def __init__(self, cycles: list[list[str]], edges: dict):
        self.cycles = cycles
        lines = ["lock-witness: acquisition-order cycle(s) detected "
                 "(potential deadlock even if this run never parked):"]
        for cyc in cycles:
            lines.append("  cycle: " + " -> ".join([*cyc, cyc[0]]))
            for a in cyc:
                for b in cyc:
                    if (a, b) in edges:
                        threads = sorted({t for t, _n in edges[(a, b)]})
                        lines.append(f"    {a} -> {b} "
                                     f"(threads: {', '.join(threads)})")
        super().__init__("\n".join(lines))


def _creation_site() -> str:
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if "lockwitness" in fn or fn.startswith("<"):
            continue
        short = fn
        for marker in ("/kube_scheduler_simulator_tpu/", "/tests/",
                       "/tools/"):
            i = fn.find(marker)
            if i >= 0:
                short = fn[i + 1:]
                break
        else:
            short = fn.rsplit("/", 1)[-1]
        return f"{short}:{frame.lineno}"
    return "?"


class Witness:
    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        # (site_a, site_b) -> {(thread name, count)} — sites, not object
        # ids: two queues created on the same line are the same CLASS of
        # lock, which is what an ordering rule is about
        self.edges: dict[tuple[str, str], set] = {}
        self.violations: list[str] = []

    # ------------------------------------------------------- thread state

    def _held(self) -> list[str]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    # ---------------------------------------------------------- recording

    def on_acquire(self, site: str, reentrant: bool) -> None:
        held = self._held()
        if site in held:
            if not reentrant:
                # same-thread reacquire of a non-reentrant lock class:
                # self-deadlock unless they are distinct instances from
                # one site — record as an ordering self-edge either way
                with self._mu:
                    self.edges.setdefault((site, site), set()).add(
                        (threading.current_thread().name, 1))
            held.append(site)
            return
        if held:
            with self._mu:
                tname = threading.current_thread().name
                for h in held:
                    if h != site:
                        self.edges.setdefault((h, site), set()).add(
                            (tname, 1))
        held.append(site)

    def on_release(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # ---------------------------------------------------------- reporting

    def cycles(self) -> list[list[str]]:
        with self._mu:
            # snapshot the edge keys: background threads (commit worker,
            # server daemons) may still be acquiring witnessed locks
            # while a test teardown walks the graph
            edges = list(self.edges)
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        from .locks import _find_cycles

        cycles = _find_cycles(graph)
        # self-edges (non-reentrant reacquire) are cycles too
        for (a, b) in edges:
            if a == b:
                cycles.append([a])
        return cycles

    def assert_no_cycles(self) -> None:
        cycles = self.cycles()
        if cycles:
            with self._mu:
                edges = dict(self.edges)
            raise LockOrderViolation(cycles, edges)

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.violations.clear()


# ----------------------------------------------------------- lock wrappers


class _WitnessLockBase:
    _reentrant = False

    def __init__(self, witness: Witness, inner, site: str):
        self._w = witness
        self._inner = inner
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._w.on_acquire(self._site, self._reentrant)
        return got

    def release(self):
        self._w.on_release(self._site)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # stdlib fork hooks (concurrent.futures.thread) re-init locks in
        # the child; delegate and drop any recorded hold
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<witnessed {self._inner!r} from {self._site}>"


class _WitnessLock(_WitnessLockBase):
    _reentrant = False


class _WitnessRLock(_WitnessLockBase):
    _reentrant = True

    # Condition integration: these are the hooks threading.Condition
    # prefers when present; wait() must drop the full recursion count
    # from the held set and restore it on wake (re-recording the edges —
    # the reacquisition after wait is a real ordering event).

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        count = state[0] if isinstance(state, tuple) else 1
        for _ in range(count):
            self._w.on_release(self._site)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        count = state[0] if isinstance(state, tuple) else 1
        for _ in range(count):
            self._w.on_acquire(self._site, self._reentrant)


_ACTIVE: Witness | None = None


def _lock_factory():
    site = _creation_site()
    return _WitnessLock(_ACTIVE, _REAL_LOCK(), f"Lock@{site}")


def _rlock_factory():
    site = _creation_site()
    return _WitnessRLock(_ACTIVE, _REAL_RLOCK(), f"RLock@{site}")


def _condition_factory(lock=None):
    if lock is None:
        site = _creation_site()
        lock = _WitnessRLock(_ACTIVE, _REAL_RLOCK(), f"Condition@{site}")
    return _REAL_CONDITION(lock)


def install() -> Witness:
    """Patch threading's lock factories; locks created BEFORE install
    stay unwitnessed (conftest installs before any test module runs).
    Returns the active Witness (idempotent)."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    _ACTIVE = Witness()
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory  # type: ignore[assignment]
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    if _ACTIVE is None:
        return
    (threading.Lock, threading.RLock,
     threading.Condition) = _ORIG_FACTORIES  # type: ignore[assignment]
    _ACTIVE = None


def active() -> Witness | None:
    return _ACTIVE
