"""Shared infrastructure for the kss-analyze static analyzers.

Pure-AST: no module under analysis is ever imported (the lock/purity
passes must run in CI without JAX or a device).  A `Module` is the parsed
tree plus its source lines (for suppression comments); a `Finding` is one
violation with a line-number-free fingerprint so the ratchet baseline
survives unrelated edits.

Suppression: a line (or the line directly above it) carrying
`# kss-analyze: allow(<rule>)` silences findings of that rule anchored
to that line.  `allow(*)` silences every rule.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

_ALLOW_RE = re.compile(r"#\s*kss-analyze:\s*allow\(([\w*,\s-]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str          # e.g. "lock-order", "blocking-under-lock"
    path: str          # repo-relative posix path
    qualname: str      # module-relative function ("Class.method" / "func")
    detail: str        # stable discriminator (lock pair, op name, ...)
    lineno: int        # anchor line (NOT part of the fingerprint)
    message: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.rule} {self.path} {self.qualname} {self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.lineno}: [{self.rule}] {self.qualname}: "
                f"{self.message or self.detail}")


@dataclass
class Module:
    path: str                  # repo-relative posix path
    modname: str               # dotted module name
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def allows(self, lineno: int, rule: str) -> bool:
        """True when `# kss-analyze: allow(rule)` sits on the line or the
        line directly above it."""
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.lines):
                m = _ALLOW_RE.search(self.lines[ln - 1])
                if m:
                    allowed = {s.strip() for s in m.group(1).split(",")}
                    if "*" in allowed or rule in allowed:
                        return True
        return False


def load_modules(root: str, package_dir: str) -> list[Module]:
    """Parse every .py file under `package_dir` (relative to repo `root`)
    into a Module.  Files that fail to parse raise — a syntax error in
    the tree is itself a finding-worthy state."""
    modules: list[Module] = []
    base = os.path.join(root, package_dir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            modname = rel[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            with open(full, encoding="utf-8") as f:
                src = f.read()
            modules.append(Module(
                path=rel, modname=modname,
                tree=ast.parse(src, filename=rel),
                lines=src.splitlines()))
    return modules


def load_module_file(root: str, rel_path: str) -> Module:
    """A single file as a Module (fixture tests analyze lone files)."""
    full = os.path.join(root, rel_path)
    rel = rel_path.replace(os.sep, "/")
    with open(full, encoding="utf-8") as f:
        src = f.read()
    modname = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
    return Module(path=rel, modname=modname,
                  tree=ast.parse(src, filename=rel), lines=src.splitlines())


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def filter_suppressed(findings: list[Finding],
                      by_path: dict[str, Module]) -> list[Finding]:
    out = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.allows(f.lineno, f.rule):
            continue
        out.append(f)
    return out
