"""kss-analyze: repo-native static analysis for the TPU scheduler
simulator (docs/static-analysis.md).

Three pure-AST analyzers over `kube_scheduler_simulator_tpu/`:

  * lock discipline  (tools/analysis/locks.py)  — lock-order inversions,
    self-deadlocks, blocking/device/serialize work under a lock;
  * device purity    (tools/analysis/purity.py) — per-pod Python loops,
    host syncs, and nondeterminism in the wave hot path;
  * observability    (tools/analysis/spans.py)  — span balance on all
    exception paths, static Prometheus name conformance.

plus the runtime lock-witness (tools/analysis/lockwitness.py) installed
by conftest.py under KSS_TPU_LOCK_WITNESS=1.

Entry points: `make analyze` / `python -m tools.analysis` (CLI), or
`run_analysis()` for tests and bench embedding.
"""

from __future__ import annotations

import os

from .common import Finding, filter_suppressed, load_modules  # noqa: F401

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_PACKAGE = "kube_scheduler_simulator_tpu"


def run_analysis(root: str | None = None,
                 package: str | None = None,
                 modules=None,
                 purity_roots=None,
                 swallow_modules=None) -> dict:
    """Run all four analyzers; returns
    {"findings": [Finding] (suppressions applied), "suppressed": int,
    "modules": int, "functions": int, "graph": CallGraph}."""
    from .callgraph import CallGraph
    from .locks import LockAnalyzer
    from .purity import PurityAnalyzer
    from .spans import SpanAnalyzer
    from .swallowed import SwallowedAnalyzer

    if modules is None:
        modules = load_modules(root or REPO_ROOT,
                               package or DEFAULT_PACKAGE)
    graph = CallGraph(modules)
    findings: list[Finding] = []
    lock_findings, lock_edges = LockAnalyzer(graph).analyze()
    findings.extend(lock_findings)
    findings.extend(PurityAnalyzer(graph, roots=purity_roots).analyze())
    findings.extend(SpanAnalyzer(modules).analyze())
    findings.extend(
        SwallowedAnalyzer(modules, hot_modules=swallow_modules).analyze())
    by_path = {m.path: m for m in modules}
    kept = filter_suppressed(findings, by_path)
    # stable order + dedup by fingerprint: one function repeating the
    # same violation on many lines (or reached through several transitive
    # paths) is ONE ratchetable fact, anchored at its first line
    seen: set[str] = set()
    uniq: list[Finding] = []
    for f in sorted(kept, key=lambda f: (f.path, f.lineno, f.rule,
                                         f.detail)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        uniq.append(f)
    return {
        "findings": uniq,
        "suppressed": len(findings) - len(kept),
        "modules": len(modules),
        "functions": len(graph.functions),
        "graph": graph,
        "lock_edges": lock_edges,
    }


def analysis_verdict(root: str | None = None) -> dict:
    """The analyzer verdict bench.py embeds in each BENCH round's JSON
    (`extra.analysis`; bench-check refuses rounds with new findings).
    Never raises — bench must not die because a tree is mid-refactor;
    an internal failure comes back as {"error": ...}."""
    try:
        from .baseline import load_baseline, partition

        result = run_analysis(root=root)
        new, old, _stale = partition(result["findings"], load_baseline())
        return {"new_findings": len(new),
                "grandfathered": len(old),
                "findings": [f.render() for f in new[:20]]}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}
