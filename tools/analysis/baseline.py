"""Ratchet baseline for kss-analyze.

The checked-in `tools/analysis/baseline.json` grandfathers known
findings: `make analyze` exits 0 while every finding is either
suppressed in-source (`# kss-analyze: allow(rule)`) or listed here with
a reason.  The ratchet only tightens:

  * a NEW finding (fingerprint absent from the baseline) fails the run —
    grandfathering it requires an explicit `--update-baseline`, which a
    reviewer sees as a baseline.json diff;
  * a STALE entry (baseline fingerprint no longer found) is reported so
    the next `--update-baseline` shrinks the file — fixed code does not
    keep its indulgence.

Fingerprints are line-number-free (rule + path + function + detail), so
unrelated edits to a file never churn the baseline.
"""

from __future__ import annotations

import json
import os

from .common import Finding

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, str]:
    """{fingerprint: reason}; missing file means an empty baseline."""
    p = path or BASELINE_PATH
    if not os.path.exists(p):
        return {}
    with open(p, encoding="utf-8") as f:
        doc = json.load(f)
    return {e["fingerprint"]: e.get("reason", "") for e in doc["entries"]}


def save_baseline(entries: dict[str, str], path: str | None = None) -> None:
    p = path or BASELINE_PATH
    doc = {
        "_comment": "kss-analyze ratchet: grandfathered findings. "
                    "Entries are only added via --update-baseline; "
                    "fixing the code and re-running --update-baseline "
                    "shrinks the file.",
        "entries": [{"fingerprint": fp, "reason": reason}
                    for fp, reason in sorted(entries.items())],
    }
    with open(p, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


def partition(findings: list[Finding], baseline: dict[str, str]
              ) -> tuple[list[Finding], list[Finding], list[str]]:
    """-> (new, grandfathered, stale_fingerprints)."""
    new: list[Finding] = []
    old: list[Finding] = []
    seen: set[str] = set()
    for f in findings:
        seen.add(f.fingerprint)
        (old if f.fingerprint in baseline else new).append(f)
    stale = [fp for fp in baseline if fp not in seen]
    return new, old, stale
