"""Intra-repo call graph over the parsed module set.

Name-based and deliberately conservative-but-approximate (the analyzers
riding on it report through a ratchet baseline, so an over-approximation
surfaces once and is triaged, never silently ignored):

  * `self.m()` resolves to `m` on the enclosing class, then on its
    repo-local base classes, then — only when the bare name is defined
    exactly once repo-wide — to that unique definition;
  * bare `f()` resolves to a module-level def in the same module or to a
    `from mod import f` target inside the repo;
  * `alias.f()` resolves through `import repo.pkg.mod as alias`;
  * anything else (callbacks, dynamic dispatch, externals) stays an
    *external* edge, recorded with its dotted text so the lock/purity
    passes can classify it (time.sleep, jnp.*, subprocess.*, ...).

Every function body is indexed once; reachability and per-function
effect summaries (locks acquired, blocking ops) are computed by the
consumers via `transitive()` fixpoints.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .common import Module, dotted_name


@dataclass
class FuncInfo:
    key: str                     # "module.modname:Class.method" unique key
    module: Module
    qualname: str                # "Class.method" / "func"
    cls: str | None
    node: ast.FunctionDef | ast.AsyncFunctionDef
    calls: list[tuple[str, int]] = field(default_factory=list)   # resolved keys
    external_calls: list[tuple[str, int]] = field(default_factory=list)
    jitted: bool = False         # decorated with / passed to jax.jit


class CallGraph:
    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.by_path = {m.path: m for m in modules}
        self.functions: dict[str, FuncInfo] = {}
        # bare function/method name -> [keys]
        self._by_name: dict[str, list[str]] = {}
        # (modname, ClassName) -> {method name -> key}
        self._methods: dict[tuple[str, str], dict[str, str]] = {}
        # (modname, ClassName) -> [base class name strings]
        self._bases: dict[tuple[str, str], list[str]] = {}
        # (modname, ClassName) -> {attr names assigned via self.X = ...}
        # (a stored callable attribute must not resolve as a method)
        self._attrs: dict[tuple[str, str], set[str]] = {}
        # (modname, cls-or-None) -> {names of defs nested inside funcs}
        self._nested: dict[tuple[str, str | None], set[str]] = {}
        # modname -> {local alias -> imported dotted target}
        self._imports: dict[str, dict[str, str]] = {}
        self._modnames = {m.modname for m in modules}
        for m in modules:
            self._index_module(m)
        for m in modules:
            self._resolve_module(m)

    # ------------------------------------------------------------ indexing

    def _index_module(self, mod: Module) -> None:
        imports: dict[str, str] = {}
        self._imports[mod.modname] = imports

        def handle_import(node: ast.AST) -> None:
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = self._abs_from(mod.modname, node)
                for a in node.names:
                    imports[a.asname or a.name] = f"{base}.{a.name}"

        for node in ast.walk(mod.tree):
            handle_import(node)

        def index_func(fn, cls: str | None, nested: bool = False) -> None:
            qual = f"{cls}.{fn.name}" if cls else fn.name
            key = f"{mod.modname}:{qual}"
            info = FuncInfo(key=key, module=mod, qualname=qual,
                            cls=cls, node=fn)
            info.jitted = self._is_jitted_def(fn)
            self.functions[key] = info
            self._by_name.setdefault(fn.name, []).append(key)
            if cls and not nested:
                # only top-level methods resolve via self.X; a def nested
                # inside a method is enclosing-scope, not class-scope
                self._methods.setdefault((mod.modname, cls), {})[fn.name] = key
            if nested:
                self._nested.setdefault(
                    (mod.modname, cls), set()).add(fn.name)

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index_func(node, None)
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index_func(sub, None, nested=True)
            elif isinstance(node, ast.ClassDef):
                self._bases[(mod.modname, node.name)] = [
                    b for b in (dotted_name(x) for x in node.bases) if b]
                attrs = self._attrs.setdefault((mod.modname, node.name),
                                               set())
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                attrs.add(tgt.attr)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        index_func(item, node.name)
                        for sub in ast.walk(item):
                            if sub is not item and isinstance(
                                    sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                                index_func(sub, node.name, nested=True)

    def _abs_from(self, modname: str, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        parts = modname.split(".")
        # a module's package is its dotted prefix; level=1 is that package
        base = parts[: len(parts) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    @staticmethod
    def _is_jitted_def(fn) -> bool:
        for dec in fn.decorator_list:
            names = []
            if isinstance(dec, ast.Call):
                names.append(dotted_name(dec.func))
                names.extend(dotted_name(a) for a in dec.args)
            else:
                names.append(dotted_name(dec))
            for name in names:
                if name and "jit" in name.split("."):
                    return True
        return False

    # ----------------------------------------------------------- resolving

    def _resolve_module(self, mod: Module) -> None:
        for key, info in self.functions.items():
            if info.module is not mod:
                continue
            for call in self._calls_in(info.node):
                target = self._resolve_call(info, call)
                if target is not None:
                    info.calls.append((target, call.lineno))
                else:
                    name = dotted_name(call.func)
                    if name:
                        info.external_calls.append((name, call.lineno))
            # f passed to jax.jit(f) anywhere in the module marks f jitted
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func) or ""
                if fname.split(".")[-1] == "jit" and node.args:
                    arg = node.args[0]
                    tgt = dotted_name(arg)
                    if tgt:
                        k = self._lookup_local(mod.modname, tgt)
                        if k and k in self.functions:
                            self.functions[k].jitted = True

    def _calls_in(self, fn) -> list[ast.Call]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                out.append(node)
        return out

    def _lookup_local(self, modname: str, bare: str) -> str | None:
        k = f"{modname}:{bare}"
        return k if k in self.functions else None

    def _resolve_call(self, info: FuncInfo, call: ast.Call) -> str | None:
        func = call.func
        name = dotted_name(func)
        if name is None:
            return None
        parts = name.split(".")
        mod = info.module
        # self.method() / cls.method()
        if parts[0] in ("self", "cls") and len(parts) == 2 and info.cls:
            return self._resolve_method(mod.modname, info.cls, parts[1])
        if len(parts) == 1:
            # bare call: a def nested in this method's enclosing scope,
            # a same-module def, or a from-import of a repo def — NEVER
            # a class-scope method (bare names don't see class scope)
            if (info.cls and parts[0] in self._nested.get(
                    (mod.modname, info.cls), ())):
                k = f"{mod.modname}:{info.cls}.{parts[0]}"
                if k in self.functions:
                    return k
            k = self._lookup_local(mod.modname, parts[0])
            if k:
                return k
            target = self._imports[mod.modname].get(parts[0])
            if target:
                return self._resolve_dotted(target)
            return None
        # alias.attr(...): through an import of a repo module
        target = self._imports[mod.modname].get(parts[0])
        if target:
            return self._resolve_dotted(".".join([target, *parts[1:]]))
        return None

    def _resolve_method(self, modname: str, cls: str,
                        method: str) -> str | None:
        seen: set[tuple[str, str]] = set()
        stack = [(modname, cls)]
        while stack:
            mk = stack.pop()
            if mk in seen:
                continue
            seen.add(mk)
            key = self._methods.get(mk, {}).get(method)
            if key:
                return key
            for base in self._bases.get(mk, []):
                bare = base.split(".")[-1]
                # base class defined in this module or imported from repo
                if (mk[0], bare) in self._methods or (mk[0], bare) in self._bases:
                    stack.append((mk[0], bare))
                else:
                    target = self._imports.get(mk[0], {}).get(
                        base.split(".")[0])
                    if target:
                        dotted = ".".join([target, *base.split(".")[1:]])
                        bmod, _, bcls = dotted.rpartition(".")
                        if bmod in self._modnames:
                            stack.append((bmod, bcls))
        # a stored callable attribute (self.cb = fn) is not a method —
        # never resolve it by name
        if method in self._attrs.get((modname, cls), ()):
            return None
        # unique bare-name fallback
        cands = self._by_name.get(method, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _resolve_dotted(self, dotted: str) -> str | None:
        """'repo.pkg.mod.func' or 'repo.pkg.mod.Class.method' -> key."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:i])
            if modname in self._modnames:
                rest = parts[i:]
                key = f"{modname}:{'.'.join(rest)}"
                if key in self.functions:
                    return key
                if len(rest) == 1:
                    # from pkg import name where name is a module
                    sub = f"{modname}.{rest[0]}"
                    if sub in self._modnames:
                        return None
                return None
        return None

    # --------------------------------------------------------- reachability

    def reachable(self, roots: list[str]) -> set[str]:
        """Function keys reachable from the given keys (roots included
        when they exist)."""
        seen: set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            for callee, _ln in self.functions[k].calls:
                if callee not in seen:
                    stack.append(callee)
        return seen

    def transitive(self, direct: dict[str, set]) -> dict[str, set]:
        """Fixpoint union of per-function facts over the call graph:
        OUT(f) = direct(f) ∪ ⋃ OUT(callee).  Handles cycles."""
        out = {k: set(direct.get(k, ())) for k in self.functions}
        changed = True
        while changed:
            changed = False
            for k, info in self.functions.items():
                acc = out[k]
                before = len(acc)
                for callee, _ln in info.calls:
                    acc |= out.get(callee, set())
                if len(acc) != before:
                    changed = True
        return out
