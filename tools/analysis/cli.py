"""`python -m tools.analysis` — the `make analyze` entry point.

Runs the three AST analyzers (lock discipline, device purity,
observability conformance) over `kube_scheduler_simulator_tpu/`, applies
in-source suppressions and the ratchet baseline, and exits nonzero on
any NEW finding.  Pure AST: needs no JAX, no device, no imports of the
analyzed modules; the full pass at HEAD runs in a couple of seconds.

Exit codes: 0 clean (possibly with grandfathered findings), 1 new
findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from . import run_analysis
from .baseline import BASELINE_PATH, load_baseline, partition, save_baseline


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kss-analyze", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from tools/)")
    ap.add_argument("--package", default="kube_scheduler_simulator_tpu",
                    help="package dir under root to analyze")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="ratchet baseline JSON path")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything as new)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(the ONLY way the grandfather list may grow)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the run result as JSON to this path "
                         "('-' for stdout)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    try:
        result = run_analysis(root=args.root, package=args.package)
    except SyntaxError as e:
        print(f"kss-analyze: parse failure: {e}", file=sys.stderr)
        return 2
    findings = result["findings"]

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, grandfathered, stale = partition(findings, baseline)

    if args.update_baseline:
        entries = {}
        for f in grandfathered:
            entries[f.fingerprint] = baseline.get(f.fingerprint, "")
        for f in new:
            entries[f.fingerprint] = "grandfathered by --update-baseline"
        save_baseline(entries, args.baseline)
        print(f"kss-analyze: baseline updated: {len(entries)} entries "
              f"({len(new)} new, {len(stale)} stale dropped) "
              f"-> {args.baseline}")
        new = []

    if not args.quiet:
        for f in new:
            print(f"NEW  {f.render()}")
        for f in grandfathered:
            print(f"OLD  {f.render()}")
        for fp in stale:
            print(f"STALE baseline entry no longer found: {fp}")
    dt = time.perf_counter() - t0
    print(f"kss-analyze: {result['modules']} modules, "
          f"{result['functions']} functions, "
          f"{len(new)} new / {len(grandfathered)} grandfathered / "
          f"{result['suppressed']} suppressed findings, "
          f"{len(stale)} stale baseline entries ({dt:.2f}s)")

    if args.json_out:
        doc = {
            "new": [f.__dict__ for f in new],
            "grandfathered": [f.__dict__ for f in grandfathered],
            "stale": stale,
            "suppressed": result["suppressed"],
            "seconds": round(dt, 3),
        }
        if args.json_out == "-":
            json.dump(doc, sys.stdout, indent=2)
            print()
        else:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2)
    if new:
        print("kss-analyze: FAIL — new findings above; fix them, add a "
              "`# kss-analyze: allow(<rule>)` with justification, or run "
              "--update-baseline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
