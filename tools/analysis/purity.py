"""Device-purity analyzer for the wave hot path (rules: pod-loop,
host-sync, nondeterminism).

Roots come from a small manifest (HOT_PATH_ROOTS below — the engine wave
entry, the whole replay module, the gang quorum slice, and the decode
chunk routing); every function reachable from them over the intra-repo
call graph is checked:

  * pod-loop — a Python `for` over a pod/node-sized iterable (pending,
    pods, nodes, or range(len(...)) of one).  The paper's whole point is
    the dense pod x node x plugin re-expression; a per-pod Python loop
    reintroduces the O(pods) interpreter serialization the fused wave
    removed.  Host-side loops that are *by design* (str building in
    decode, commit bookkeeping) are ratcheted or carry allow comments.
  * host-sync — `.item()`, `float()`, `int()`, `np.asarray()`/
    `np.array()` on a traced value forces a device->host transfer and a
    blocking sync inside the wave.  Statically "traced" is undecidable,
    so the rule fires on the syntactic forms inside *jitted* functions,
    and on `.item()` anywhere in the hot path.
  * nondeterminism — `time.*` / `random.*` / `np.random.*` inside
    jitted code: a traced clock or RNG bakes one trace-time value into
    the compiled executable, silently breaking replay determinism.
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph
from .common import Finding, dotted_name

# the hot-path manifest: (module suffix, qualname-or-* ) roots
HOT_PATH_ROOTS: list[tuple[str, str]] = [
    ("framework.engine", "SchedulerEngine._schedule_wave"),
    ("framework.engine", "SchedulerEngine._profile_wave_run"),
    ("framework.engine", "SchedulerEngine._profile_wave_attempt"),
    ("framework.engine", "_WaveCommitter.on_chunk"),
    ("framework.engine", "_WaveCommitter._commit"),
    ("framework.replay", "*"),
    ("framework.gang", "quorum_slice"),
    ("store.decode", "decode_chunk_into"),
    ("store.decode", "decode_all_parallel"),
    # lazy materialization entry points (PR 9): the result-store read
    # path and the on-demand chunk routing serve API reads concurrently
    # with live waves — they must stay loop-free and host-sync-free too
    ("store.resultstore", "ResultStore.get_stored_result"),
    ("store.resultstore", "ResultStore.take_deferred"),
    ("store.resultstore", "_merge_snapshot"),
    ("store.lazy", "*"),
    ("store.reflector", "LazyReflections._drain"),
    ("store.reflector", "LazyReflections._apply"),
    # device-resident results (PR 10): the D2H entry points serve API
    # reads concurrently with live waves, and the device-side
    # attribution reduction runs per chunk inside the wave — both must
    # stay loop-free and host-sync-free (framework.replay is a root
    # already and covers _CompactChunks.materialize/_DeviceAttribution)
    ("store.native_decode", "decode_chunk_start"),
    ("store.native_decode", "decode_pod_fused"),
    # multi-session serving (PR 11): the session registry sits on every
    # routed request, concurrent with all sessions' live waves — lookup,
    # listing and the shared-shell stats must stay loop-free and
    # host-sync-free (the lock rules additionally watch the registry
    # lock package-wide: no engine wave, deep copy or blocking call may
    # run under SessionManager._mu)
    ("server.sessions", "SessionManager.get"),
    ("server.sessions", "SessionManager.list_sessions"),
    ("server.sessions", "SessionManager.stats"),
    ("server.sessions", "SimulationSession.touch"),
    ("server.sessions", "SimulationSession.register_stream"),
    ("server.sessions", "SimulationSession.unregister_stream"),
    # speculative default wave (PR 13): the streaming round loop, its
    # conflict-oracle host walk and the engine shell run inside every
    # wave — they must stay free of per-pod Python loops and eager
    # host syncs on the compact groups (the accumulator emits whole
    # chunks through gather_to_host, the one sanctioned crossing)
    ("framework.engine", "SchedulerEngine._speculative_wave"),
    ("parallel.speculative", "replay_speculative_stream"),
    ("parallel.speculative", "_spec_run"),
    ("parallel.speculative", "_interaction_cut"),
    ("framework.gang", "aligned_cut"),
    # cross-session fused dispatch (PR 16): the coordinator's join/
    # stack/split path runs inside every speculative round of every
    # session — it must stay free of per-pod loops, eager host syncs on
    # stacked device pytrees, and (via the lock rules) device calls
    # under the coordinator condition
    ("parallel.fuse", "*"),
    # columnar data plane (PR 17): the node-table build/patch and the
    # column read surface run once per wave over up to 100k-node arrays
    # — a per-ROW Python loop here (columnar-row-loop below) undoes the
    # vectorization the columns exist for.  Bounded opaque-row fallbacks
    # iterate opaque_positions(), never the row arrays themselves.
    ("state.nodes", "build_node_table_columnar"),
    ("state.nodes", "patch_node_table_columnar"),
    ("state.compile", "_node_delta"),
    ("cluster.columnar", "NodeColumns.alloc_matrix"),
    ("cluster.columnar", "NodeColumns.extended_names"),
    ("cluster.columnar", "NodeColumns.allowed_pods"),
    ("cluster.columnar", "NodeColumns.unschedulable"),
    ("cluster.columnar", "_LabelRows.column"),
]

BIG_ITERABLES = {"pending", "pods", "nodes"}
HOST_SYNC_METHODS = {"item"}
HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")

# compact-host-sync: the replay's heavy per-chunk groups may be LIVE
# DEVICE arrays (device-resident results); an eager np.asarray /
# np.ascontiguousarray on one of these fields outside the materialization
# path silently re-introduces the in-wave D2H the residency design
# removed.  _CompactChunks.host()/materialize() (which route through
# parallel.mesh.gather_to_host on a generic value, not a field access)
# are the only sanctioned crossings.
COMPACT_FIELDS = {"packed", "raw8", "raw16", "raw32"}
COMPACT_SYNC_CALLS = HOST_SYNC_CALLS | {
    "np.ascontiguousarray", "numpy.ascontiguousarray", "jax.device_get"}

# columnar-row-loop: per-ROW arrays of the columnar banks
# (cluster/columnar.py) — one entry per stored object.  A Python `for`
# directly over one of these (or enumerate/zip/range(len(...)) of one)
# re-serializes O(rows) work the columns were built to vectorize.  The
# per-COLUMN dicts (res, label_cols, req) are ~dozens of entries and are
# deliberately NOT listed; neither are single-row subscripts like
# `taints[row]`.
COLUMNAR_ROW_ARRAYS = {"names", "rv", "uid", "created", "manifests",
                       "opaque", "deleted", "taints", "nonzero"}


def resolve_roots(graph: CallGraph,
                  roots: list[tuple[str, str]] | None = None) -> list[str]:
    keys: list[str] = []
    for mod_suffix, qual in roots or HOT_PATH_ROOTS:
        for key, info in graph.functions.items():
            modname = key.partition(":")[0]
            if not (modname == mod_suffix
                    or modname.endswith("." + mod_suffix)):
                continue
            if qual == "*" or info.qualname == qual:
                keys.append(key)
    return keys


class PurityAnalyzer:
    def __init__(self, graph: CallGraph,
                 roots: list[tuple[str, str]] | None = None):
        self.graph = graph
        self.root_keys = resolve_roots(graph, roots)
        self.reachable = graph.reachable(self.root_keys)

    def analyze(self) -> list[Finding]:
        findings: list[Finding] = []
        for key in sorted(self.reachable):
            info = self.graph.functions[key]
            findings.extend(self._check_function(info))
        return findings

    def _check_function(self, info) -> list[Finding]:
        out: list[Finding] = []
        jitted = info.jitted
        for node in ast.walk(info.node):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                big = self._big_iterable(node.iter)
                if big:
                    out.append(Finding(
                        rule="pod-loop", path=info.module.path,
                        qualname=info.qualname, detail=f"for over {big}",
                        lineno=node.lineno,
                        message=f"Python for-loop over {big} in the wave "
                                "hot path (should be a fused tensor op)"))
                col = self._columnar_row_iterable(node.iter)
                if col:
                    out.append(Finding(
                        rule="columnar-row-loop", path=info.module.path,
                        qualname=info.qualname, detail=f"for over {col}",
                        lineno=node.lineno,
                        message=f"Python for-loop over columnar row array "
                                f"{col}: per-row work on the data plane "
                                "must be a vectorized numpy op (bounded "
                                "opaque-row fallbacks iterate "
                                "opaque_positions())"))
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                last = name.split(".")[-1]
                if (name in COMPACT_SYNC_CALLS
                        and self._compact_field_arg(node)):
                    out.append(Finding(
                        rule="compact-host-sync", path=info.module.path,
                        qualname=info.qualname,
                        detail=f"{name}({self._compact_field_arg(node)})",
                        lineno=node.lineno,
                        message=f"{name} on a replay compact field outside "
                                "_CompactChunks.materialize: device-resident "
                                "chunks must cross D2H only through "
                                "cc.host()/materialize()"))
                if last in HOST_SYNC_METHODS and "." in name:
                    out.append(Finding(
                        rule="host-sync", path=info.module.path,
                        qualname=info.qualname, detail=f"{last}()",
                        lineno=node.lineno,
                        message=f"{name}() forces a device->host sync in "
                                "the wave hot path"))
                elif name in HOST_SYNC_CALLS and jitted:
                    out.append(Finding(
                        rule="host-sync", path=info.module.path,
                        qualname=info.qualname, detail=name,
                        lineno=node.lineno,
                        message=f"{name} on a traced value inside jitted "
                                "code materializes to host"))
                elif jitted and any(name.startswith(p)
                                    for p in NONDET_PREFIXES):
                    out.append(Finding(
                        rule="nondeterminism", path=info.module.path,
                        qualname=info.qualname, detail=name,
                        lineno=node.lineno,
                        message=f"{name}() inside jitted code bakes a "
                                "trace-time value into the executable"))
        return out

    @staticmethod
    def _compact_field_arg(call: ast.Call) -> str | None:
        """The `.packed`/`.raw*` attribute inside the call's arguments,
        if any (e.g. np.asarray(cc.packed[ci][:m]) -> "packed")."""
        for arg in call.args:
            for sub in ast.walk(arg):
                if (isinstance(sub, ast.Attribute)
                        and sub.attr in COMPACT_FIELDS):
                    return sub.attr
        return None

    def _columnar_row_iterable(self, it: ast.AST) -> str | None:
        """`x.names` / `enumerate(bank.rv)` / `range(len(cols.uid))` —
        an iteration over a per-row columnar array (attribute access
        only: bare names and single-row subscripts don't match)."""
        if (isinstance(it, ast.Attribute)
                and it.attr in COLUMNAR_ROW_ARRAYS):
            return dotted_name(it) or it.attr
        if isinstance(it, ast.Call):
            cname = dotted_name(it.func)
            if cname in ("range", "enumerate", "reversed", "sorted", "zip"):
                for arg in it.args:
                    inner = self._columnar_row_iterable(arg)
                    if inner:
                        return f"{cname}({inner})"
                for arg in it.args:
                    if (isinstance(arg, ast.Call)
                            and dotted_name(arg.func) == "len"
                            and arg.args):
                        inner = self._columnar_row_iterable(arg.args[0])
                        if inner:
                            return f"{cname}(len({inner}))"
        return None

    def _big_iterable(self, it: ast.AST) -> str | None:
        name = dotted_name(it)
        if name and name.split(".")[-1] in BIG_ITERABLES:
            return name
        if isinstance(it, ast.Call):
            cname = dotted_name(it.func)
            if cname in ("range", "enumerate", "reversed", "sorted", "zip"):
                for arg in it.args:
                    inner = self._big_iterable(arg)
                    if inner:
                        return f"{cname}({inner})"
                # range(len(pending)) shape
                for arg in it.args:
                    if (isinstance(arg, ast.Call)
                            and dotted_name(arg.func) == "len"
                            and arg.args):
                        inner = self._big_iterable(arg.args[0])
                        if inner:
                            return f"{cname}(len({inner}))"
        return None
