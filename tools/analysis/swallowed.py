"""Swallowed-exception analyzer (rule: swallowed-exception).

A bare `except ...: pass` on a hot-path module is how faults become
invisible: the wave failure protocol (docs/fault-injection.md) can only
classify and retry/degrade what actually SURFACES, and the chaos gate
can only assert on what is COUNTED.  This rule flags exception handlers
whose body is entirely silent — only `pass` / `continue` / `break` /
`...` — on the modules the fault seams thread through.  A handler that
re-raises, records a tracing tap, logs, or mutates state is doing
*something* with the failure and is not flagged.

Existing reasoned sites are grandfathered with in-source
`# kss-analyze: allow(swallowed-exception)` comments carrying their
justification (the suppression mechanism of tools/analysis/common.py);
new silent swallows on these modules fail `make analyze`.
"""

from __future__ import annotations

import ast

from .common import Finding, Module

RULE = "swallowed-exception"

# the hot-path modules the fault seams thread through: a silent swallow
# here hides exactly the failures the chaos gate injects
HOT_MODULES = (
    "framework/engine.py",
    "framework/replay.py",
    "framework/gang.py",
    "store/decode.py",
    "store/lazy.py",
    "store/reflector.py",
    "store/resultstore.py",
    "server/sessions.py",
    "server/di.py",
    "cluster/kubeapi.py",
)

_SILENT = (ast.Pass, ast.Continue, ast.Break)


def _is_silent(stmt: ast.stmt) -> bool:
    if isinstance(stmt, _SILENT):
        return True
    # a lone `...` (Ellipsis) expression is a pass in disguise
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant))


def _exc_label(handler: ast.ExceptHandler) -> str:
    t = handler.type
    if t is None:
        return "bare"
    if isinstance(t, ast.Tuple):
        return ",".join(ast.unparse(e) for e in t.elts)
    return ast.unparse(t)


class SwallowedAnalyzer:
    def __init__(self, modules: list[Module], hot_modules=None):
        self.modules = modules
        self.hot_modules = tuple(hot_modules) if hot_modules is not None \
            else HOT_MODULES

    def analyze(self) -> list[Finding]:
        findings: list[Finding] = []
        for mod in self.modules:
            if not mod.path.endswith(self.hot_modules):
                continue
            findings.extend(self._check_module(mod))
        return findings

    def _check_module(self, mod: Module) -> list[Finding]:
        out: list[Finding] = []
        # enclosing-function map for qualnames
        qual_of: dict[int, str] = {}

        def walk(node, prefix=""):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{prefix}{child.name}"
                    # recurse FIRST so nested functions stamp their own
                    # nodes; the outer setdefault then only fills the
                    # rest — otherwise sibling nested functions would
                    # share the outer qualname and their findings would
                    # collide into one ratchet fingerprint
                    walk(child, q + ".")
                    for n in ast.walk(child):
                        qual_of.setdefault(id(n), q)
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}{child.name}.")
                else:
                    walk(child, prefix)

        walk(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not node.body or not all(_is_silent(s) for s in node.body):
                continue
            label = _exc_label(node)
            qual = qual_of.get(id(node), "<module>")
            out.append(Finding(
                rule=RULE, path=mod.path, qualname=qual,
                detail=f"except {label}", lineno=node.lineno,
                message=(f"silent `except {label}: pass` swallows the "
                         "failure with no tap, log, re-raise or state "
                         "change — surface it (TRACER.inc / re-raise) or "
                         "justify with an allow comment"),
            ))
        return out
