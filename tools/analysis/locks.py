"""Lock-discipline analyzer (rules: lock-order, self-deadlock,
blocking-under-lock, device-under-lock, serialize-under-lock).

Lock discovery is by assignment: `self.X = threading.Lock()/RLock()/
Condition()` inside a class gives the lock identity `module:Class.X`;
a module-level `X = threading.Lock()` gives `module:X`.  Holds are
tracked structurally: `with self.X:` bodies, and `self.X.acquire()` ..
`self.X.release()` runs inside one statement list.

While a lock is held, every call is classified:

  * a call that (transitively, over the intra-repo call graph) acquires
    a DIFFERENT lock contributes an ordering edge A -> B; a cycle among
    the edges is a lock-order inversion — exactly the PR 3
    `kubeapi._rv_int` deadlock shape, reported before any thread ever
    interleaves into it;
  * a call that reacquires the SAME non-reentrant Lock is a
    self-deadlock (that bug class again, single-lock variant);
  * a call reaching a blocking operation (time.sleep, subprocess,
    socket/urllib I/O, file open, Thread.join, native codec entry
    points) is blocking-under-lock;
  * a call reaching JAX dispatch (jnp.* / jax.*) is device-under-lock —
    device work can take arbitrary milliseconds and must never happen
    on a lock every reader shares;
  * json/deepcopy/marshal serialization under a lock is
    serialize-under-lock — not a deadlock, but exactly the hidden
    serialization Gavel-style throughput claims die on, and the shape
    PR 2 had to move off the store lock.

Condition variables: `.wait()` on the HELD condition releases it by
contract and is never flagged; `notify`/`notify_all` are lock-internal.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .callgraph import CallGraph
from .common import Finding, dotted_name

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
REENTRANT_FACTORIES = {"RLock", "Condition"}  # Condition wraps an RLock

BLOCKING_PREFIXES = (
    "time.sleep", "subprocess.", "socket.", "urllib.request.",
    "requests.", "select.",
)
BLOCKING_EXACT = {"open", "input"}
BLOCKING_METHODS = {"urlopen", "recv", "connect",
                    "check_call", "check_output", "run_until_complete"}
# `.join` blocks only on thread-like receivers (str.join / os.path.join
# are pure); match by receiver name
_THREADISH = ("thread", "worker", "proc")
DEVICE_PREFIXES = ("jnp.", "jax.")
NATIVE_BASES = {"lib", "_lib", "native"}
SERIALIZE_PREFIXES = ("json.dumps", "json.loads", "copy.deepcopy",
                      "pickle.", "yaml.")
SERIALIZE_METHODS = {"marshal"}


@dataclass(frozen=True)
class LockDef:
    lock_id: str       # "module:Class.attr" or "module:attr"
    reentrant: bool


def _call_name(node: ast.Call) -> str | None:
    return dotted_name(node.func)


def _classify_external(name: str) -> str | None:
    """rule name for an unresolved (external) call, or None."""
    if any(name.startswith(p) for p in DEVICE_PREFIXES):
        return "device-under-lock"
    if name in BLOCKING_EXACT or any(
            name.startswith(p) for p in BLOCKING_PREFIXES):
        return "blocking-under-lock"
    parts = name.split(".")
    if len(parts) >= 2 and parts[0] in NATIVE_BASES:
        return "blocking-under-lock"
    if len(parts) >= 2 and parts[-1] in BLOCKING_METHODS:
        return "blocking-under-lock"
    if (len(parts) >= 2 and parts[-1] == "join"
            and any(t in parts[-2].lower() for t in _THREADISH)):
        return "blocking-under-lock"
    if any(name.startswith(p) for p in SERIALIZE_PREFIXES):
        return "serialize-under-lock"
    if len(parts) >= 2 and parts[-1] in SERIALIZE_METHODS:
        return "serialize-under-lock"
    return None


class LockAnalyzer:
    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.locks: dict[str, LockDef] = {}
        self._discover_locks()
        # per function: set of lock_ids it DIRECTLY acquires
        self._direct_acquires: dict[str, set[str]] = {}
        # per function: set of (rule, opname) effects it DIRECTLY has
        self._direct_effects: dict[str, set[tuple[str, str]]] = {}
        for key, info in graph.functions.items():
            acq, eff = self._function_direct_facts(info)
            self._direct_acquires[key] = acq
            self._direct_effects[key] = eff
        self._trans_acquires = graph.transitive(self._direct_acquires)
        self._trans_effects = graph.transitive(self._direct_effects)

    # ----------------------------------------------------------- discovery

    def _discover_locks(self) -> None:
        for mod in self.graph.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        self._maybe_lock_assign(mod, node.name, sub)
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign):
                    self._maybe_lock_assign(mod, None, stmt)

    def _maybe_lock_assign(self, mod, cls: str | None,
                           assign: ast.Assign) -> None:
        if not isinstance(assign.value, ast.Call):
            return
        name = _call_name(assign.value) or ""
        factory = name.split(".")[-1]
        if factory not in LOCK_FACTORIES:
            return
        if not (name.startswith("threading.") or name == factory):
            return
        for tgt in assign.targets:
            attr = None
            if (cls and isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                attr = f"{cls}.{tgt.attr}"
            elif cls is None and isinstance(tgt, ast.Name):
                attr = tgt.id
            if attr:
                lid = f"{mod.modname}:{attr}"
                self.locks[lid] = LockDef(
                    lid, reentrant=factory in REENTRANT_FACTORIES)

    def _lock_for_expr(self, info, expr: ast.AST) -> LockDef | None:
        """LockDef for `self.X` / module-level `X` in this function."""
        mod = info.module.modname
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and info.cls):
            # walk the class MRO the same way method resolution does:
            # a lock assigned in a repo-local base class is the same lock
            cand = f"{mod}:{info.cls}.{expr.attr}"
            if cand in self.locks:
                return self.locks[cand]
            for lid, d in self.locks.items():
                m, _, qual = lid.partition(":")
                if m == mod and qual.endswith(f".{expr.attr}"):
                    return None  # other class's lock: not resolvable here
            # unique attr-name fallback across the repo (self._lock of a
            # mixin/base defined elsewhere)
            hits = [d for lid, d in self.locks.items()
                    if lid.partition(":")[2].split(".")[-1] == expr.attr]
            if len(hits) == 1:
                return hits[0]
            return None
        if isinstance(expr, ast.Name):
            cand = f"{mod}:{expr.id}"
            return self.locks.get(cand)
        return None

    # ------------------------------------------------------- direct facts

    def _function_direct_facts(self, info):
        """(locks acquired anywhere in fn, (rule, op) effects anywhere in
        fn) — used for the *transitive* summaries of callees."""
        acquires: set[str] = set()
        effects: set[tuple[str, str]] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    d = self._lock_for_expr(info, item.context_expr)
                    if d:
                        acquires.add(d.lock_id)
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name is None:
                    continue
                if name.endswith(".acquire"):
                    base = node.func.value
                    d = self._lock_for_expr(info, base)
                    if d:
                        acquires.add(d.lock_id)
                rule = _classify_external(name)
                if rule and not self._is_resolved_call(info, node):
                    effects.add((rule, name))
        return acquires, effects

    def _is_resolved_call(self, info, call: ast.Call) -> bool:
        ln = call.lineno
        return any(l == ln for _t, l in info.calls)

    # ------------------------------------------------------------ analysis

    def analyze(self) -> tuple[list[Finding], dict[tuple[str, str], list]]:
        findings: list[Finding] = []
        # ordering edges: (held, acquired) -> [(path, qual, line, via)]
        edges: dict[tuple[str, str], list] = {}
        for key, info in self.graph.functions.items():
            self._walk_held(info, info.node.body, [], findings, edges)
        findings.extend(self._order_findings(edges))
        return findings, edges

    def _walk_held(self, info, body: list, held: list[LockDef],
                   findings, edges) -> None:
        i = 0
        while i < len(body):
            stmt = body[i]
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    d = self._lock_for_expr(info, item.context_expr)
                    if d:
                        self._note_acquire(info, item.context_expr.lineno,
                                           inner, d, findings, edges)
                        inner = inner + [d]
                # check calls in the with-line items themselves first
                for item in stmt.items:
                    self._check_expr(info, item.context_expr, held,
                                     findings, edges)
                self._walk_held(info, stmt.body, inner, findings, edges)
                i += 1
                continue
            # linear acquire()/release() within this statement list
            d = self._acquire_stmt(info, stmt)
            if d is not None:
                self._note_acquire(info, stmt.lineno, held, d,
                                   findings, edges)
                # scan forward to the matching release in this block
                j = i + 1
                inner_stmts = []
                while j < len(body):
                    if self._release_stmt(info, body[j]) == d.lock_id:
                        break
                    inner_stmts.append(body[j])
                    j += 1
                self._walk_held(info, inner_stmts, held + [d],
                                findings, edges)
                i = j + 1
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor,
                                 ast.Try)):
                # header expressions with the current held set, then the
                # nested blocks (each exactly once)
                for header in ("test", "iter"):
                    sub = getattr(stmt, header, None)
                    if sub is not None:
                        self._check_expr(info, sub, held, findings, edges)
                for attr in ("body", "orelse", "finalbody"):
                    subs = getattr(stmt, attr, None)
                    if subs:
                        self._walk_held(info, subs, held, findings, edges)
                for h in getattr(stmt, "handlers", []):
                    self._walk_held(info, h.body, held, findings, edges)
            elif not isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.ClassDef)):
                # simple statement: every call in it runs under `held`
                self._check_expr(info, stmt, held, findings, edges)
            i += 1

    def _acquire_stmt(self, info, stmt) -> LockDef | None:
        if (isinstance(stmt, (ast.Expr, ast.Assign))
                and isinstance(stmt.value, ast.Call)):
            name = _call_name(stmt.value)
            if name and name.endswith(".acquire"):
                return self._lock_for_expr(info, stmt.value.func.value)
        return None

    def _release_stmt(self, info, stmt) -> str | None:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            name = _call_name(stmt.value)
            if name and name.endswith(".release"):
                d = self._lock_for_expr(info, stmt.value.func.value)
                if d:
                    return d.lock_id
        return None

    def _note_acquire(self, info, lineno: int, held: list[LockDef],
                      d: LockDef, findings, edges) -> None:
        for h in held:
            if h.lock_id == d.lock_id:
                if not d.reentrant:
                    findings.append(Finding(
                        rule="self-deadlock", path=info.module.path,
                        qualname=info.qualname, detail=d.lock_id,
                        lineno=lineno,
                        message=f"non-reentrant {d.lock_id} reacquired "
                                "while already held on this path"))
                continue
            edges.setdefault((h.lock_id, d.lock_id), []).append(
                (info.module.path, info.qualname, lineno, "direct"))

    def _check_expr(self, info, expr, held, findings, edges) -> None:
        """Flag calls in an expression (or simple statement) executed with
        `held` locks; nested function bodies and lambdas run later and are
        pruned."""
        if not held:
            return
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                self._check_call(info, node, held, findings, edges)
            stack.extend(ast.iter_child_nodes(node))

    def _check_call(self, info, call: ast.Call, held: list[LockDef],
                    findings, edges) -> None:
        name = _call_name(call)
        if name is None:
            return
        held_ids = {h.lock_id for h in held}
        # condition-variable wait on the held lock releases it: skip
        if name.endswith(".wait") or name.endswith(".wait_for"):
            d = self._lock_for_expr(info, call.func.value)
            if d and d.lock_id in held_ids:
                return
        if name.endswith((".acquire", ".release", ".notify",
                          ".notify_all", ".locked")):
            return  # structural lock ops handled elsewhere
        # resolved repo call: pull the callee's transitive summaries
        target = None
        for t, ln in info.calls:
            if ln == call.lineno and self._matches_target(t, name):
                target = t
                break
        if target is not None:
            for lid in self._trans_acquires.get(target, ()):  # ordering
                for h in held:
                    if lid == h.lock_id:
                        if not h.reentrant:
                            findings.append(Finding(
                                rule="self-deadlock",
                                path=info.module.path,
                                qualname=info.qualname,
                                detail=f"{h.lock_id} via {target}",
                                lineno=call.lineno,
                                message=f"holds {h.lock_id} and calls "
                                        f"{target} which reacquires it"))
                    else:
                        edges.setdefault((h.lock_id, lid), []).append(
                            (info.module.path, info.qualname,
                             call.lineno, target))
            for rule, op in self._trans_effects.get(target, ()):
                findings.append(self._effect_finding(
                    info, call.lineno, held, rule, op, via=target))
            return
        rule = _classify_external(name)
        if rule:
            findings.append(self._effect_finding(
                info, call.lineno, held, rule, name, via=None))

    @staticmethod
    def _matches_target(target_key: str, call_name: str) -> bool:
        bare = target_key.rsplit(".", 1)[-1].rsplit(":", 1)[-1]
        return call_name.split(".")[-1] == bare

    def _effect_finding(self, info, lineno, held, rule, op, via):
        held_s = "+".join(sorted(h.lock_id for h in held))
        det = f"{op} holding {held_s}"
        msg = (f"{op} while holding {held_s}"
               + (f" (via {via})" if via else ""))
        return Finding(rule=rule, path=info.module.path,
                       qualname=info.qualname, detail=det,
                       lineno=lineno, message=msg)

    # -------------------------------------------------------- order cycles

    def _order_findings(self, edges) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings: list[Finding] = []
        for cycle in _find_cycles(graph):
            # anchor the finding at each edge site participating in the
            # cycle so suppression/baseline can target the real code
            cyc = set(cycle)
            pairs = [(a, b) for (a, b) in edges
                     if a in cyc and b in cyc and a != b]
            loop = " -> ".join([*cycle, cycle[0]])
            for (a, b) in sorted(pairs):
                for (path, qual, lineno, via) in edges[(a, b)]:
                    findings.append(Finding(
                        rule="lock-order", path=path, qualname=qual,
                        detail=f"{a} -> {b} in cycle [{loop}]",
                        lineno=lineno,
                        message=f"acquisition order {a} -> {b} "
                                f"participates in cycle {loop}"
                                + (f" (via {via})"
                                   if via != "direct" else "")))
        return findings


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Elementary cycles via SCC decomposition (every SCC with more than
    one node, reported as the sorted node list)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs
