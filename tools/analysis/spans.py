"""Observability-conformance analyzer (rules: unbalanced-span,
metric-name, label-name).

Span balance: `Tracer.span()` is a context manager; the ONLY form that
guarantees the end fires on every exception path — including across the
commit-worker thread boundary PR 5 parents explicitly — is
`with TRACER.span(...)`.  Any call to `.span(...)` that is not the
context expression of a `with` item (bare call, stored handle, manual
`__enter__`) is an unbalanced-span finding.

Metric names: every literal name passed to `TRACER.count/inc/observe`
and every literal span name must already satisfy the strict Prometheus
exposition rules PR 5's `validate_exposition()` enforces at scrape time
(`[a-zA-Z_:][a-zA-Z0-9_:]*`; label keywords `[a-zA-Z_][a-zA-Z0-9_]*`).
Runtime sanitization would *silently rename* a bad name, so the check is
static: the name a reader greps for must be the name exported.  Span
names additionally feed `span_<name>_seconds_total` families and pass
through the same gate.
"""

from __future__ import annotations

import ast
import re

from .common import Finding, Module, dotted_name

# mirror utils/tracing.py's regexes (no import: these passes must run
# without the package's dependency closure)
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

TRACER_BASES = {"TRACER", "tracer", "_tracer"}
METRIC_METHODS = {"count", "inc", "observe"}


class SpanAnalyzer:
    def __init__(self, modules: list[Module]):
        self.modules = modules

    def analyze(self) -> list[Finding]:
        findings: list[Finding] = []
        for mod in self.modules:
            findings.extend(self._check_module(mod))
        return findings

    def _check_module(self, mod: Module) -> list[Finding]:
        out: list[Finding] = []
        with_contexts: set[int] = set()   # id() of calls used as with-items
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_contexts.add(id(item.context_expr))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._tracer_method(node)
            if target is None:
                continue
            base, method = target
            if method == "span":
                if id(node) not in with_contexts:
                    out.append(Finding(
                        rule="unbalanced-span", path=mod.path,
                        qualname=self._span_name(node) or base,
                        detail=f"{base}.span not context-managed",
                        lineno=node.lineno,
                        message=f"{base}.span(...) outside a `with`: the "
                                "span end is not guaranteed on exception "
                                "paths"))
                self._check_name(node, mod, out, span=True)
            elif method in METRIC_METHODS:
                self._check_name(node, mod, out, span=False)
        return out

    @staticmethod
    def _tracer_method(call: ast.Call) -> tuple[str, str] | None:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        base = dotted_name(f.value)
        if base is None:
            return None
        last = base.split(".")[-1]
        if last in TRACER_BASES or base in TRACER_BASES:
            return last, f.attr
        return None

    @staticmethod
    def _span_name(call: ast.Call) -> str | None:
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    def _check_name(self, call: ast.Call, mod: Module,
                    out: list[Finding], span: bool) -> None:
        name = self._span_name(call)
        if name is not None and not _METRIC_NAME_RE.match(name):
            kind = "span" if span else "metric"
            out.append(Finding(
                rule="metric-name", path=mod.path, qualname=name,
                detail=f"invalid {kind} name {name!r}",
                lineno=call.lineno,
                message=f"{kind} name {name!r} fails the Prometheus name "
                        "rules (validate_exposition would only see a "
                        "silently sanitized alias)"))
        labels: list[str] = []
        for kw in call.keywords:
            if kw.arg is None:
                # **{...}: literal dict keys are checkable
                if isinstance(kw.value, ast.Dict):
                    labels.extend(
                        k.value for k in kw.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str))
            elif kw.arg not in ("n", "parent", "value"):
                labels.append(kw.arg)
        for label in labels:
            # keyword syntax already guarantees identifier shape; the
            # checkable surface is **{...} dicts and the reserved
            # double-underscore prefix Prometheus claims for itself
            if not _LABEL_NAME_RE.match(label) or label.startswith("__"):
                out.append(Finding(
                    rule="label-name", path=mod.path,
                    qualname=name or "?",
                    detail=f"invalid label {label!r}",
                    lineno=call.lineno,
                    message=f"label name {label!r} fails the Prometheus "
                            "label rules (reserved or malformed)"))
