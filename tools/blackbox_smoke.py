"""Black-box smoke gate: `make blackbox-smoke` / `python -m tools.blackbox_smoke`.

Arms a ONE-RULE fault plan through the real KSS_TPU_FAULT_PLAN env
surface, runs an engine wave with the retry budget pinned to 0 (so the
transient fault aborts the wave instead of healing), and asserts that a
well-formed post-mortem dump landed in KSS_TPU_BLACKBOX_DIR — schema-
checked by utils.blackbox.validate_dump, which requires:

  * the fault trip on the record (seam + error + classification) and a
    classified cause;
  * the protocol's action (wave.abort here);
  * the speculative round history that preceded the fault;
  * non-empty counter deltas for the failing wave;
  * a device fingerprint with an explicit hbm_available flag.

This is the cheapest end-to-end proof that a crashed wave ships its own
evidence (docs/fault-injection.md) — `make test` runs it before the
tier-1 suite.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    dump_dir = tempfile.mkdtemp(prefix="kss-blackbox-smoke-")
    plan = {"seed": 7, "rules": [
        {"seam": "replay.decision_fetch", "nth": 2, "error": "runtime"},
    ]}
    plan_path = os.path.join(dump_dir, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump(plan, fh)
    # env BEFORE the simulator imports: faults arms KSS_TPU_FAULT_PLAN
    # at module load, and the dump dir must be in force at abort time
    os.environ["KSS_TPU_FAULT_PLAN"] = "@" + plan_path
    os.environ["KSS_TPU_BLACKBOX_DIR"] = dump_dir
    os.environ["KSS_TPU_WAVE_MAX_RETRIES"] = "0"
    # pin the toggles the assertions depend on: an inherited
    # KSS_TPU_SPECULATIVE=0 (the parity lever) or KSS_TPU_BLACKBOX=0
    # must not fail `make test` spuriously — the smoke asserts the
    # default-configuration behavior
    os.environ["KSS_TPU_SPECULATIVE"] = "1"
    os.environ["KSS_TPU_BLACKBOX"] = "1"

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.blackbox import validate_dump
    from kube_scheduler_simulator_tpu.utils.faults import InjectedFault

    store = ObjectStore()
    for n in make_nodes(6, seed=1):
        store.create("nodes", n)
    for p in make_pods(24, seed=2):
        store.create("pods", p)
    engine = SchedulerEngine(
        store, plugin_config=PluginSetConfig(enabled=["NodeResourcesFit"]),
        chunk=8)
    surfaced = None
    try:
        engine.schedule_pending()
    except InjectedFault as e:
        surfaced = e
    finally:
        engine.close()
    if surfaced is None:
        print("blackbox-smoke: FAIL — the armed fault never surfaced "
              "(retry budget 0 should abort the wave)", file=sys.stderr)
        return 1

    files = sorted(glob.glob(os.path.join(dump_dir, "blackbox-*.json")))
    if not files:
        print(f"blackbox-smoke: FAIL — no dump landed in {dump_dir}",
              file=sys.stderr)
        return 1
    with open(files[-1], encoding="utf-8") as fh:
        doc = json.load(fh)
    try:
        res = validate_dump(doc, require_fault=True, require_rounds=True)
    except ValueError as e:
        print(f"blackbox-smoke: FAIL — malformed dump {files[-1]}: {e}",
              file=sys.stderr)
        return 1
    print(json.dumps({
        "ok": True,
        "dump": files[-1],
        "reason": doc["reason"],
        "cause": doc["cause"],
        "event_kinds": res["kinds"],
        "deltas": len(doc["counter_deltas"]),
        "hbm_available": doc["device"]["hbm_available"],
    }))
    print(f"blackbox-smoke: ok — {doc['reason']} dump at {files[-1]} "
          f"({sum(res['kinds'].values())} events, "
          f"{len(doc['counter_deltas'])} counter deltas)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
