"""Soak gate for the SLO-driven autopilot: `make bench-soak` /
`python -m tools.soak`.

Sustained multi-session traffic against a LIVE SimulatorServer with the
autopilot on (docs/autopilot.md), asserting the closed loop's three
promises end to end:

  * a well-behaved `standard` tenant under continuous arrival churn
    (models/workloads.py make_churn_workload) keeps its rolling p99
    wave latency inside the configured SLO target for the whole run;
  * an overloaded `best-effort` tenant is load-shed — its HTTP
    submissions get 429 with a Retry-After header AND a
    retryAfterSeconds body field, every single time — and the shed
    LIFTS once the overload stops (hysteresis both ways);
  * a tenant hit by an injected structural device fault walks the
    degradation ladder down and RECOVERS to rung 0 (device_resident)
    by run end — the autopilot never pins a session degraded.

Sessions are also created and deleted mid-run (session churn), so the
controller's per-session memory is pruned while it runs, and the final
black box must validate (`autopilot.decide` events carry the full
{effector, session, from, to, reason} shape).

The verdict JSON feeds docs/bench/bench_check.py (SOAK_* rounds):
soak_p99_wave_seconds and soak_shed_rate must not regress across
rounds and soak_recovered_to_rung0 must stay true.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Knobs must land before the simulator package is imported: the SLO
# window is read at SLOTracker construction (utils/blackbox.py) and the
# autopilot cadence/target at controller construction.  A tight window
# + fast ticks keep the whole soak under ~a minute on CPU while still
# exercising hysteresis (>= HYSTERESIS_TICKS real controller ticks per
# wave burst).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["KSS_TPU_AUTOPILOT"] = "1"
os.environ["KSS_TPU_AUTOPILOT_INTERVAL_S"] = "0.1"
os.environ["KSS_TPU_AUTOPILOT_SLO_TARGET_P99_S"] = "0.25"
os.environ["KSS_TPU_AUTOPILOT_SHED_QOS"] = "best-effort"
os.environ["KSS_TPU_SLO_WINDOW"] = "16"
os.environ["KSS_TPU_DEGRADE_PROBE_WAVES"] = "3"
# the telemetry-history ring must be ON (an inherited KSS_TPU_HISTORY=0
# would make the causal-reconstruction assertions vacuous) and deep
# enough that a ~0.1s-tick soak never scrolls the breach era away: the
# autopilot tick itself feeds the ring (control/autopilot.py pulls its
# evidence through FEEDER.sample), one row per tick
os.environ["KSS_TPU_HISTORY"] = "1"
os.environ["KSS_TPU_HISTORY_CAPACITY"] = "4096"

SLO_TARGET_S = 0.25
STD, BE, DEG = "soak-std", "soak-be", "soak-deg"

# every distinct pending-pod count is its own compiled scan shape
# (framework/replay.py _workload_scan_key includes the xs shapes), so
# the driver pads each churn wave up to a multiple of this quantum and
# precompiles the padded shapes during warmup — steady-state churn must
# measure scheduling latency, not a compile per novel Poisson draw
WAVE_QUANTUM = 16


def _req(port: int, method: str, path: str, body=None):
    """-> (status, headers dict, parsed body|None) without raising on
    4xx/5xx — the 429 shed contract IS the thing under test."""
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            raw = resp.read()
            return resp.status, dict(resp.headers), (
                json.loads(raw) if raw else None)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, dict(e.headers), (json.loads(raw) if raw else None)


def _fill(store, pods: list[dict]) -> None:
    for p in pods:
        store.create("pods", p)


def _pods(n: int, seed: int, prefix: str, cheap: bool = False) -> list[dict]:
    """make_pods with unique names per burst — the soak submits many
    independent bursts into one store.  `cheap` shrinks requests to
    filler size so padding pods never exhaust capacity (an unbound pod
    would carry into the next wave and change its compiled shape)."""
    from kube_scheduler_simulator_tpu.models.workloads import make_pods

    pods = make_pods(n, seed=seed)
    for i, p in enumerate(pods):
        p["metadata"]["name"] = f"{prefix}-{i:05d}"
        if cheap:
            p["spec"]["containers"][0]["resources"]["requests"] = {
                "cpu": "50m", "memory": str(64 << 20)}
    return pods


def _slot_pods(n: int, seed: int, prefix: str) -> list[dict]:
    """Filler pods in the exact churn-pod shape (app-labeled, tiny
    requests): the compiled scan's schema and statics follow the pod
    features present in the wave, so padding with a DIFFERENT pod shape
    would compile a second executable family per tick."""
    return [{
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"{prefix}-{i:05d}", "namespace": "default",
                     "labels": {"app": f"job-{(seed + i) % 4}"}},
        "spec": {
            "containers": [{
                "name": "main",
                "image": "registry.k8s.io/pause:3.9",
                "resources": {"requests": {"cpu": "50m",
                                           "memory": str(64 << 20)}},
            }],
        },
    } for i in range(n)]


def _drop_pods(store, bound: bool, prefix: str = "") -> None:
    """Delete bound pods (completed work leaves) or pending ones (the
    backlog clients gave up on) so wave shapes stay uniform and node
    capacity never saturates across a long soak."""
    pods, _rv = store.list("pods")
    for p in pods:
        meta = p["metadata"]
        if (bool((p.get("spec") or {}).get("nodeName")) == bound
                and meta["name"].startswith(prefix)):
            store.delete("pods", meta["name"],
                         meta.get("namespace") or "default")


def _calibrate_overload(eng, store) -> int:
    """Pods per overload wave sized so ONE wave lasts ~2x the SLO
    target on THIS machine — the breach must come from sustained load,
    not a lucky slow box."""
    probe = 200
    _fill(store, _pods(probe, seed=11, prefix="soak-cal"))
    eng.schedule_pending()          # compile warmup, not timed
    _fill(store, _pods(probe, seed=12, prefix="soak-cal2"))
    t0 = time.perf_counter()
    eng.schedule_pending()
    per_pod = max(time.perf_counter() - t0, 1e-4) / probe
    _drop_pods(store, bound=True)
    _drop_pods(store, bound=False)
    return min(max(int(2 * SLO_TARGET_S / per_pod), 400), 2000)


def run_soak(ticks: int = 18) -> dict:
    from kube_scheduler_simulator_tpu.control import CONTROLS
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_churn_workload, make_nodes)
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.utils import faults
    from kube_scheduler_simulator_tpu.utils.blackbox import (
        BLACKBOX, validate_dump)

    t_start = time.perf_counter()
    failures: list[str] = []
    mgr = SessionManager(max_sessions=12, idle_ttl=0,
                         start_scheduler=False)
    srv = SimulatorServer(mgr, port=0)
    srv.start(block=False)
    port = srv.port
    shed_responses = 0
    bad_shed = 0            # 429s missing the Retry-After contract
    canaries = 0
    churned = 0
    deg_tripped = False
    try:
        for sid, qos in ((STD, "standard"), (BE, "best-effort"),
                         (DEG, "standard")):
            code, _h, _b = _req(port, "POST", "/api/v1/sessions",
                                {"id": sid, "qos": qos})
            if code != 201:
                failures.append(f"session {sid} create -> {code}")
        engines = {sid: mgr.get(sid).di.engine for sid in (STD, BE, DEG)}
        stores = {sid: mgr.get(sid).di.store for sid in (STD, BE, DEG)}

        # ---- cluster seeding --------------------------------------
        nodes, schedule = make_churn_workload(
            n_nodes=48, ticks=ticks, seed=5, arrival_rate=8.0,
            departure_rate=4.0, name_prefix="soak")
        for n in nodes:
            stores[STD].create("nodes", n)
        for n in make_nodes(96, seed=6):
            stores[BE].create("nodes", n)
        for n in make_nodes(24, seed=7):
            stores[DEG].create("nodes", n)

        batch = _calibrate_overload(engines[BE], stores[BE])

        # warm + flush: pay each session's one-time scan compiles (one
        # per padded wave shape) up front, then roll them out of the
        # SLO window with fast same-shape waves so the measured loop
        # (and the p99 gate) sees steady-state churn, not compiler
        # latency
        window = int(os.environ["KSS_TPU_SLO_WINDOW"])
        for shape in (WAVE_QUANTUM, 2 * WAVE_QUANTUM):
            _fill(stores[STD], _slot_pods(shape, seed=60 + shape,
                                          prefix=f"soak-warm-{shape}"))
            engines[STD].schedule_pending()
        for t in range(window):
            _fill(stores[STD], _slot_pods(WAVE_QUANTUM, seed=800 + t,
                                          prefix=f"soak-stdflush-{t}"))
            engines[STD].schedule_pending()
        _drop_pods(stores[STD], bound=True)   # warmup filler leaves
        for t in range(3 * window):
            _fill(stores[BE], _pods(WAVE_QUANTUM, seed=700 + t,
                                    prefix=f"soak-flush-{t}", cheap=True))
            engines[BE].schedule_pending()
            time.sleep(0.02)
            # the calibration's compile wave may have tripped the shed;
            # flush until the controller reopens the tenant
            if t >= window and not CONTROLS.shed_state(BE)[0]:
                break
        _drop_pods(stores[BE], bound=True)
        if CONTROLS.shed_state(BE)[0]:
            failures.append("best-effort tenant still shed after the "
                            "warmup flush — loop would start vacuous")

        # the degradation-ladder leg: one structural device fault early
        # in the run, scoped to DEG only
        faults.arm(faults.FaultPlan([
            faults.FaultRule("replay.scan_dispatch", nth=2,
                             error="memory", times=1, sessions=[DEG])],
            seed=1))

        # ---- churn + overload main loop ---------------------------
        for t in range(ticks):
            # standard tenant: HTTP create/delete per the churn
            # schedule, padded to the precompiled wave quantum, then
            # one wave
            for pod in schedule[t]["create"]:
                code, _h, _b = _req(
                    port, "POST", f"/api/v1/sessions/{STD}/pods", pod)
                if code != 201:
                    failures.append(f"std pod create -> {code} (tick {t})")
            for name in schedule[t]["delete"]:
                _req(port, "DELETE",
                     f"/api/v1/sessions/{STD}/pods/default/{name}")
            created = len(schedule[t]["create"])
            pad = -created % WAVE_QUANTUM or WAVE_QUANTUM * (not created)
            if pad:
                _fill(stores[STD], _slot_pods(pad, seed=900 + t,
                                              prefix=f"soak-pad-{t}"))
            engines[STD].schedule_pending()
            _drop_pods(stores[STD], bound=True, prefix="soak-pad-")

            # best-effort tenant: one HTTP canary probes the shed
            # state; while open, the bulk overload lands and runs a
            # deliberately over-target wave
            canary = _pods(1, seed=100 + t, prefix=f"soak-canary-{t}")[0]
            code, hdrs, body = _req(
                port, "POST", f"/api/v1/sessions/{BE}/pods", canary)
            canaries += 1
            if code == 429:
                shed_responses += 1
                retry_hdr = hdrs.get("Retry-After")
                retry_body = (body or {}).get("retryAfterSeconds")
                if (retry_hdr is None or not str(retry_hdr).isdigit()
                        or not isinstance(retry_body, int)
                        or retry_body < 1):
                    bad_shed += 1
            elif code == 201:
                _fill(stores[BE], _pods(
                    batch, seed=200 + t, prefix=f"soak-be-{t}"))
                engines[BE].schedule_pending()
                _drop_pods(stores[BE], bound=True)   # completed work
            else:
                failures.append(f"be canary -> {code} (tick {t})")

            # faulted tenant: fresh small waves every tick — the first
            # trips the armed structural fault, the rest are the clean
            # probe waves the ladder needs to climb back
            _fill(stores[DEG], _pods(
                24, seed=300 + t, prefix=f"soak-deg-{t}"))
            engines[DEG].schedule_pending()
            _drop_pods(stores[DEG], bound=True)
            if engines[DEG].result_mode() != "device_resident":
                deg_tripped = True

            # session churn: short-lived best-effort tenants appear
            # and vanish while the controller runs
            if t % 4 == 1:
                code, _h, _b = _req(port, "POST", "/api/v1/sessions",
                                    {"id": f"soak-churn-{t}",
                                     "qos": "best-effort"})
                if code == 201:
                    churned += 1
            elif t % 4 == 3:
                _req(port, "DELETE", f"/api/v1/sessions/soak-churn-{t - 2}")
            time.sleep(0.05)    # let controller ticks interleave

        if not deg_tripped:
            failures.append("structural fault never tripped the ladder "
                            "(vacuous recovery check)")
        if shed_responses == 0:
            failures.append("overloaded best-effort tenant was never shed")
        if bad_shed:
            failures.append(
                f"{bad_shed}/{shed_responses} shed responses missing the "
                "Retry-After header / retryAfterSeconds body contract")

        # ---- cooldown: overload stops, the shed must lift ---------
        # the still-pending bulk backlog is dropped first (clients gave
        # up), then recovery is probed through the REAL client surface:
        # HTTP POSTs that keep 429ing while shed and succeed once the
        # controller reopens the gate.  Nothing feeds the engine
        # directly here — a quiesced shed session must recover on its
        # own (no new waves is no evidence of ongoing breach), which is
        # exactly what real backed-off clients would observe.
        _drop_pods(stores[BE], bound=False)
        shed_lifted = False
        for t in range(6 * window):
            probe = _pods(1, seed=500 + t, prefix=f"soak-cool-{t}",
                          cheap=True)[0]
            code, hdrs, body = _req(
                port, "POST", f"/api/v1/sessions/{BE}/pods", probe)
            if code == 201:
                shed_lifted = True
                engines[BE].schedule_pending()   # bind the probe pod
                _drop_pods(stores[BE], bound=True)
                break
            if code != 429:
                failures.append(f"cooldown probe -> {code} (tick {t})")
                break
            retry_hdr = hdrs.get("Retry-After")
            if retry_hdr is None or not str(retry_hdr).isdigit():
                failures.append(
                    f"cooldown 429 missing Retry-After (tick {t})")
            time.sleep(0.05)
        if not shed_lifted:
            failures.append("shed never lifted after the overload stopped")
        else:
            # the probe wave just recorded into a window still full of
            # breach-era percentiles, so on a slow box the controller
            # may CORRECTLY re-shed for one more quiesce/recover
            # cycle; post-recovery health means submissions are
            # accepted again within a bounded horizon, not that the
            # very next request wins a race against the closing gate
            code = None
            for t in range(6 * window):
                code, _h, _b = _req(
                    port, "POST", f"/api/v1/sessions/{BE}/pods",
                    _pods(1, seed=999 + t,
                          prefix=f"soak-after-{t}")[0])
                if code != 429:
                    break
                time.sleep(0.05)
            if code != 201:
                failures.append(f"post-recovery submit -> {code}")

        recovered = engines[DEG].result_mode() == "device_resident"
        if not recovered:
            failures.append("degradation ladder did not recover to "
                            f"rung 0: {engines[DEG].result_mode()}")

        std_slo = mgr.get(STD, touch=False).info().get("slo") or {}
        std_p99 = std_slo.get("p99WaveSeconds")
        if std_p99 is None or std_p99 > SLO_TARGET_S:
            failures.append(
                f"standard tenant p99 {std_p99} breached the "
                f"{SLO_TARGET_S}s target under churn")

        ap = mgr.stats().get("autopilot") or {}
        if not ap.get("decisions"):
            failures.append("autopilot made zero decisions all soak")
        if ap.get("failsafes"):
            failures.append(f"autopilot tripped its fail-safe "
                            f"{ap['failsafes']}x during a clean soak")

        # ---- causal reconstruction from the history ring ----------
        # the whole breach -> shed -> recovery arc must be readable
        # back out of the columnar ring (docs/metrics.md "History &
        # correlation"), and every shed decision's recorded evidence
        # must match the ring AT ITS INDEX — provenance, not vibes
        from kube_scheduler_simulator_tpu.utils.history import HISTORY
        win = HISTORY.window(series=["slo.p99", "autopilot.shed"],
                             session=BE, since=0)
        p99_col = win["series"].get(f"slo.p99{{session={BE}}}") or []
        shed_col = (win["series"].get(f"autopilot.shed{{session={BE}}}")
                    or [])
        hist_rows = len(win["index"])
        first_shed = next(
            (i for i, v in enumerate(shed_col) if v == 1.0), None)
        breach_before_shed = first_shed is not None and any(
            v is not None and v > SLO_TARGET_S
            for v in p99_col[:first_shed + 1])
        shed_lift_in_ring = first_shed is not None and any(
            v == 0.0 for v in shed_col[first_shed:])
        if first_shed is None:
            failures.append("history ring never recorded the "
                            "best-effort shed (autopilot.shed == 1)")
        else:
            if not breach_before_shed:
                failures.append(
                    "history ring shows no p99 breach at or before "
                    "the first shed sample — the causal order "
                    "breach -> shed is not reconstructible")
            if not shed_lift_in_ring:
                failures.append("history ring never recorded the shed "
                                "lifting (autopilot.shed back to 0)")

        evidence_checked = 0
        for d in (ap.get("lastDecisions") or {}).get(BE) or []:
            if d.get("effector") != "shed":
                continue
            evd = d.get("evidence") or {}
            idx = evd.get("historyIndex")
            if not isinstance(idx, int):
                failures.append("shed decision carries no historyIndex: "
                                f"{d.get('reason')}")
                continue
            ring_p99 = HISTORY.value(f"slo.p99{{session={BE}}}", idx)
            ev_p99 = evd.get("p99WaveSeconds")
            if (ring_p99 is None) != (ev_p99 is None) or (
                    ring_p99 is not None
                    and abs(ring_p99 - ev_p99) > 1e-9):
                failures.append(
                    f"shed evidence p99 {ev_p99} != ring row {idx} "
                    f"value {ring_p99} — provenance broken")
            # the row was sampled BEFORE the decision applied, so it
            # must show the pre-transition shed state
            ring_shed = HISTORY.value(
                f"autopilot.shed{{session={BE}}}", idx)
            want = 0.0 if d.get("to") == "shedding" else 1.0
            if ring_shed != want:
                failures.append(
                    f"ring row {idx} shed flag {ring_shed} != "
                    f"pre-decision state {want} ({d.get('from')} -> "
                    f"{d.get('to')})")
            if d.get("to") == "open":
                # the lift rule: back inside the 0.8x recovery band,
                # or quiesced (no fresh waves — frozen window carries
                # no evidence of ongoing breach)
                if not (ev_p99 is None
                        or ev_p99 <= 0.8 * SLO_TARGET_S
                        or int(evd.get("freshWaves") or 0) <= 0):
                    failures.append(
                        f"shed lifted outside the recovery band: p99 "
                        f"{ev_p99} with {evd.get('freshWaves')} fresh "
                        f"waves")
            evidence_checked += 1
        if evidence_checked == 0:
            failures.append("no shed decision evidence to check "
                            "against the ring (vacuous provenance)")

        doc, _path = BLACKBOX.dump("soak", write=False)
        try:
            validate_dump(doc)
        except Exception as e:  # noqa: BLE001 — verdict reports it
            failures.append(f"black box failed validation: {e}")
    finally:
        faults.disarm()
        srv.shutdown()

    return {
        "ok": not failures,
        "failures": failures,
        "soak_p99_wave_seconds": std_p99,
        "soak_shed_rate": round(shed_responses / max(canaries, 1), 3),
        "soak_recovered_to_rung0": recovered,
        "all_shed_had_retry_after": shed_responses > 0 and bad_shed == 0,
        "shed_responses": shed_responses,
        "shed_lifted": shed_lifted,
        "slo_target_p99_s": SLO_TARGET_S,
        "history_rows": hist_rows,
        "history_breach_before_shed": breach_before_shed,
        "history_shed_lift_recorded": shed_lift_in_ring,
        "shed_evidence_checked": evidence_checked,
        "ticks": ticks,
        "overload_batch": batch,
        "sessions_churned": churned,
        "autopilot": {k: ap.get(k) for k in
                      ("ticks", "decisions", "failsafes",
                       "decisionsByEffector")},
        "seconds": round(time.perf_counter() - t_start, 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kss-soak", description=__doc__)
    ap.add_argument("--ticks", type=int, default=18)
    ap.add_argument("json_out", nargs="?", default=None)
    args = ap.parse_args(argv)
    verdict = run_soak(ticks=args.ticks)
    print(json.dumps(verdict, indent=2))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(verdict, fh, indent=2)
    if not verdict["ok"]:
        for f in verdict["failures"]:
            print(f"soak: FAIL — {f}", file=sys.stderr)
        return 1
    print(f"soak: ok — p99 {verdict['soak_p99_wave_seconds']:.3f}s, "
          f"{verdict['shed_responses']} sheds (all Retry-After), "
          f"recovered to rung 0, {verdict['seconds']}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
