"""Chaos verification harness: `make chaos` / `python -m tools.chaos`.

Runs concurrent multi-session scheduling waves under randomized, SEEDED
fault plans (kube_scheduler_simulator_tpu/utils/faults.py) and asserts
the wave failure protocol's invariants (docs/fault-injection.md):

  * waves COMPLETE under injected faults — via uncommitted-suffix retry
    or the degradation ladder — instead of aborting the backlog;
  * annotations and binds are BIT-IDENTICAL to the fault-free run of
    the same workload for every session;
  * gang atomicity holds: every PodGroup is all-bound or all-unbound;
  * per-session isolation: every fault targets one session (the plan's
    session filter) and the neighbor's results are still byte-identical
    to ITS fault-free run;
  * session admission survives create/evict faults with a consistent
    registry;
  * no lock-order cycles under the runtime lock witness
    (KSS_TPU_LOCK_WITNESS=1 — `make chaos` sets it).

Each seed derives one deterministic plan, so a failure prints the exact
reproducing command.  The quick single-seed verdict also rides every
bench round (`extra.chaos`) and `bench_check.py` refuses rounds whose
chaos run failed.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

DEFAULT_SEEDS = 3
FAULTED, NEIGHBOR = "chaos-a", "chaos-b"


def _build_cluster(store, seed: int, n_nodes: int, n_pods: int,
                   gangs: int, gang_members: int):
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_gang_workload, make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.coscheduling import (
        ensure_podgroup_resource)

    ensure_podgroup_resource(store)
    for n in make_nodes(n_nodes, seed=seed):
        store.create("nodes", n)
    for p in make_pods(n_pods, seed=seed):
        store.create("pods", p)
    pgs, pods = make_gang_workload(gangs, gang_members, seed=seed + 1,
                                   name_prefix=f"cg{seed}")
    for pg in pgs:
        store.create("podgroups", pg)
    for p in pods:
        store.create("pods", p)
    return [pg["metadata"]["name"] for pg in pgs]


def _engine(store, session: str, chunk: int):
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.plugins.coscheduling import Coscheduling
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig

    cfg = PluginSetConfig(enabled=["NodeResourcesFit", "Coscheduling"],
                          custom={"Coscheduling": Coscheduling()})
    eng = SchedulerEngine(store, plugin_config=cfg, chunk=chunk)
    eng.session = session
    return eng


def _plan_for(seed: int, target: str):
    """The seed's randomized plan, every rule scoped to `target` — the
    isolation invariant needs a provably unfaulted neighbor."""
    from kube_scheduler_simulator_tpu.utils.faults import FaultPlan, FaultRule

    rng = random.Random(seed * 7919)
    rules = [
        # transient scan/fetch faults: heal via uncommitted-suffix retry
        FaultRule("replay.scan_dispatch", nth=rng.randint(1, 3),
                  error="runtime", times=1, sessions=[target]),
        # mid-round speculative fault (the default wave's own seam):
        # committed round chunks stand through the gang-cut watermark,
        # the uncommitted suffix retries recompiled against current
        # store state — byte parity with the fault-free run must hold
        FaultRule("speculative.round", nth=rng.randint(1, 2),
                  error="runtime", times=1, sessions=[target]),
        # fused-dispatch fault: fires on the requesting thread BEFORE it
        # joins a batch, so only the target's wave aborts and retries —
        # batch-mates (the neighbor) must be untouched (parallel/fuse.py)
        FaultRule("fuse.dispatch", nth=rng.randint(1, 2),
                  error="runtime", times=1, sessions=[target]),
        FaultRule("replay.decision_fetch", p=0.15, error="io", times=2,
                  sessions=[target]),
        # structural fault: steps the degradation ladder down a rung
        FaultRule("replay.scan_dispatch", nth=rng.randint(5, 8),
                  error="memory", times=1, sessions=[target]),
        # decode fault: heals on re-read (or via wave retry when it
        # surfaces through an in-wave reflect materialization)
        FaultRule("decode.chunk", nth=rng.randint(1, 2), error="runtime",
                  times=1, sessions=[target]),
        # write-back conflicts: heal under the reflector's own backoff
        FaultRule("reflector.write_back", p=0.2, error="conflict", times=2,
                  sessions=[target]),
        # compile fault: first failure is transient, wave retry rebuilds
        FaultRule("compile.build", nth=1, error="runtime", times=1,
                  sessions=[target]),
    ]
    return FaultPlan(rules, seed=seed)


def _collect_state(store, session: str) -> dict:
    """{pod name: (nodeName, annotations)} — reads run under the
    session's tracer scope so read-path fault rules can target them;
    the one-retry wrapper IS the heals-on-re-read invariant."""
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    def read():
        out = {}
        with TRACER.session_scope(session):
            pods, _ = store.list("pods")
        for p in pods:
            meta = p.get("metadata") or {}
            out[meta.get("name", "")] = (
                (p.get("spec") or {}).get("nodeName"),
                dict(meta.get("annotations") or {}))
        return out

    try:
        return read()
    except Exception:
        # a transient injected decode fault surfaces to its first
        # reader and MUST heal on the next read without poisoning the
        # chunk (store/lazy.py) — a second failure is a real bug
        return read()


def _run_once(seed: int, plan, shape: dict) -> dict:
    """One concurrent two-session run; returns per-session state, gang
    names, per-session result modes and any drive errors."""
    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.utils import faults

    sessions = {}
    gang_names = {}
    for i, sid in enumerate((FAULTED, NEIGHBOR)):
        store = ObjectStore()
        gang_names[sid] = _build_cluster(
            store, seed=seed + 50 * i, n_nodes=shape["nodes"],
            n_pods=shape["pods"], gangs=shape["gangs"],
            gang_members=shape["gang_members"])
        sessions[sid] = (store, _engine(store, sid, chunk=shape["chunk"]))

    barrier = threading.Barrier(len(sessions))
    errors: dict[str, BaseException] = {}

    def drive(sid: str):
        _store, eng = sessions[sid]
        barrier.wait()
        try:
            eng.schedule_pending()
        except BaseException as e:  # noqa: BLE001 — the verdict reports it
            errors[sid] = e

    # set the global to exactly `plan` (None = fault-free reference) and
    # RESTORE the previous plan after: an operator's env-armed
    # KSS_TPU_FAULT_PLAN must survive a bench-embedded chaos verdict
    prev = faults.current_plan()
    prev_retries = os.environ.get("KSS_TPU_WAVE_MAX_RETRIES")
    if plan is not None:
        faults.arm(plan)
        # the protocol completes a wave iff its retry budget covers the
        # transient faults landing in it; size the budget to this
        # plan's worst case (every bounded transient rule trips in ONE
        # wave) so the gate asserts protocol CORRECTNESS, not a lucky
        # fault spread.  An unbounded budget would hide retry storms —
        # the exact worst case keeps the bound meaningful.
        budget = sum(
            (r.times or 0) for r in plan.rules
            if r.error in ("runtime", "io", "timeout", "conflict"))
        os.environ["KSS_TPU_WAVE_MAX_RETRIES"] = str(max(budget, 3))
    else:
        faults.disarm()
    try:
        threads = [threading.Thread(target=drive, args=(sid,), daemon=True,
                                    name=f"chaos-{sid}")
                   for sid in sessions]
        for t in threads:
            t.start()
        for t, sid in zip(threads, sessions):
            t.join(timeout=120)
            if t.is_alive():
                # a wedged wave is its own failure class: report it
                # instead of reading a store the wave still mutates
                errors.setdefault(sid, TimeoutError(
                    "wave wedged: thread still alive after 120s"))
        state = {sid: (_collect_state(store, sid)
                       if sid not in errors else {})
                 for sid, (store, _e) in sessions.items()}
    finally:
        if prev is not None:
            faults.arm(prev)
        else:
            faults.disarm()
        if plan is not None:
            if prev_retries is None:
                os.environ.pop("KSS_TPU_WAVE_MAX_RETRIES", None)
            else:
                os.environ["KSS_TPU_WAVE_MAX_RETRIES"] = prev_retries
    modes = {sid: eng.result_mode() for sid, (_s, eng) in sessions.items()}
    for sid, (_store, eng) in sessions.items():
        if sid not in errors:  # never block closing a wedged engine
            eng.close()
    return {"state": state, "gangs": gang_names, "errors": errors,
            "modes": modes}


def _gang_atomicity_failures(state: dict, gang_names: list[str]) -> list[str]:
    bad = []
    for g in gang_names:
        members = {n: s for n, (s, _a) in state.items()
                   if n.startswith(g + "-")}
        bound = [n for n, s in members.items() if s]
        if bound and len(bound) != len(members):
            bad.append(f"gang {g}: {len(bound)}/{len(members)} bound")
    return bad


def _session_lifecycle_check(seed: int) -> list[str]:
    """Session create/evict seams: an injected construction failure
    must release the reservation (the id is re-creatable), an injected
    teardown failure must not wedge admission, and the registry stays
    consistent throughout."""
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.utils import faults

    failures: list[str] = []
    mgr = SessionManager(max_sessions=3, idle_ttl=0, start_scheduler=False)
    plan = faults.FaultPlan([
        faults.FaultRule("session.create", nth=1, error="runtime"),
        faults.FaultRule("session.evict", nth=1, error="runtime"),
    ], seed=seed)
    try:
        with faults.armed(plan):
            try:
                mgr.create("c1")
                failures.append("session.create fault did not surface")
            except faults.InjectedFault:
                pass
            try:
                mgr.create("c1")   # reservation released: same id admits
                mgr.create("c2")   # at capacity now (default + c1 + c2)
                mgr.create("c3")   # evicts LRU c1 through the faulted path
            except Exception as e:  # noqa: BLE001 — verdict reports
                failures.append(f"admission after faults failed: {e!r}")
        ids = {s["id"] for s in mgr.list_sessions()}
        if ids != {"default", "c2", "c3"}:
            failures.append(f"registry inconsistent after faults: {ids}")
    finally:
        mgr.shutdown()
    return failures


def _autopilot_failsafe_check(seed: int) -> list[str]:
    """The autopilot.decide seam (control/autopilot.py): a fault while
    a tick applies its decisions must revert EVERY effector to the
    static-knob defaults (CONTROLS.reset()), count the failsafe, and
    leave the controller able to keep ticking — fail-safe, never
    fail-wedged.  The rule is UNSCOPED because the controller thread
    runs outside any session tracer scope."""
    from kube_scheduler_simulator_tpu.control import CONTROLS
    from kube_scheduler_simulator_tpu.control.autopilot import Autopilot
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.utils import faults
    from kube_scheduler_simulator_tpu.utils.blackbox import SLO

    failures: list[str] = []
    mgr = SessionManager(max_sessions=4, idle_ttl=0,
                         start_scheduler=False)
    try:
        mgr.create("ap-a", qos="best-effort")
        ap = Autopilot(mgr, interval=3600, slo_target=0.05)

        def waves(seconds, n=70):   # fill the whole SLO window
            for _ in range(n):
                SLO.observe_wave("ap-a", seconds, pods=10)

        waves(1.0)
        ap.tick()
        ap.tick()                   # breach x2 ticks -> shed applied
        if not CONTROLS.shed_state("ap-a")[0]:
            failures.append("autopilot never shed under synthetic "
                            "breach")
        # a second effector's state must ALSO revert on the trip
        CONTROLS.set_budget_weight("ap-a", 2.0)
        waves(0.001)                # recovered: the next ticks plan unshed
        plan = faults.FaultPlan([
            faults.FaultRule("autopilot.decide", nth=1, error="runtime")],
            seed=seed)
        with faults.armed(plan):
            ap.tick()
            ap.tick()               # ok x2 ticks -> decision -> trip
        if plan.stats()["rules"][0]["trips"] != 1:
            failures.append("autopilot.decide fault never tripped "
                            "(vacuous)")
        if ap.stats()["failsafes"] != 1:
            failures.append("failsafe counter not bumped after the trip")
        if CONTROLS.stats() != {}:
            failures.append("controls not reverted to static defaults "
                            f"after the trip: {CONTROLS.stats()}")
        # the controller survives: clean ticks run, and a renewed
        # breach sheds again from the reset state
        ap.tick()
        waves(1.0)
        ap.tick()
        ap.tick()
        if not CONTROLS.shed_state("ap-a")[0]:
            failures.append("controller wedged after the failsafe: "
                            "renewed breach no longer sheds")
    finally:
        CONTROLS.reset()
        mgr.shutdown()
    return failures


def run_seed(seed: int, shape: dict, witness=None) -> dict:
    """Run one seed: fault-free reference, chaos run, invariants.
    Returns {ok, seed, failures, injected, modes}."""
    failures: list[str] = []
    plan = _plan_for(seed, FAULTED)
    # chaos FIRST: the scan-compile seam only fires on cache misses, and
    # the reference run would otherwise warm every shape
    chaos = _run_once(seed, plan, shape)
    ref = _run_once(seed, None, shape)
    for sid, err in chaos["errors"].items():
        failures.append(f"{sid}: wave did not complete: {err!r}")
    for sid, err in ref["errors"].items():
        failures.append(f"{sid}: fault-free reference failed: {err!r}")
    injected = sum(r["trips"] for r in plan.stats()["rules"])
    if injected == 0:
        failures.append("plan injected nothing — the seed is vacuous")
    for sid in (FAULTED, NEIGHBOR):
        got, want = chaos["state"].get(sid), ref["state"].get(sid)
        if got is None or want is None:
            continue
        if got != want:
            diff = sorted(
                set(k for k in want if want[k] != got.get(k))
                | (set(got) - set(want)))[:4]
            role = "faulted" if sid == FAULTED else "NEIGHBOR (isolation)"
            failures.append(
                f"{sid} ({role}): state diverged from fault-free run at "
                f"{diff}")
        failures.extend(
            f"{sid}: {m}" for m in _gang_atomicity_failures(
                got, chaos["gangs"][sid]))
    failures.extend(_session_lifecycle_check(seed))
    failures.extend(_autopilot_failsafe_check(seed))
    if witness is not None:
        try:
            witness.assert_no_cycles()
        except AssertionError as e:
            failures.append(f"lock witness: {e}")
    dump_path = None
    if failures:
        # a red chaos run ships its own evidence: snapshot the wave
        # black box (event ring, counter deltas, armed plan, device
        # fingerprint) next to the reproducing seed so debugging starts
        # from the dump, not from a re-run (docs/fault-injection.md)
        import tempfile

        from kube_scheduler_simulator_tpu.utils.blackbox import BLACKBOX

        _doc, dump_path = BLACKBOX.dump(
            "chaos_failure", write=True,
            directory=(os.environ.get("KSS_TPU_BLACKBOX_DIR")
                       or tempfile.gettempdir()))
    return {"ok": not failures, "seed": seed, "failures": failures,
            "injected": injected, "modes": chaos["modes"],
            "dump": dump_path}


QUICK_SHAPE = {"nodes": 5, "pods": 14, "gangs": 1, "gang_members": 3,
               "chunk": 6}
FULL_SHAPE = {"nodes": 8, "pods": 26, "gangs": 2, "gang_members": 3,
              "chunk": 8}


def chaos_verdict(seeds: int = DEFAULT_SEEDS, seed_base: int = 1,
                  quick: bool = False, witness=None) -> dict:
    """The machine-readable verdict `make chaos` gates on and bench
    rounds embed as extra.chaos."""
    shape = QUICK_SHAPE if quick else FULL_SHAPE
    t0 = time.perf_counter()
    results = [run_seed(seed_base + i, shape, witness=witness)
               for i in range(seeds)]
    return {
        "ok": all(r["ok"] for r in results),
        "seeds": [r["seed"] for r in results],
        "injected_total": sum(r["injected"] for r in results),
        "failures": [f for r in results for f in
                     (f"seed {r['seed']}: {m}" for m in r["failures"])],
        # black-box dumps written for failing seeds (None entries for
        # green seeds are dropped): the first thing to open on a red run
        "dumps": [r["dump"] for r in results if r.get("dump")],
        "seconds": round(time.perf_counter() - t0, 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kss-chaos", description=__doc__)
    ap.add_argument("--seeds", type=int, default=DEFAULT_SEEDS)
    ap.add_argument("--seed-base", type=int, default=1)
    ap.add_argument("--quick", action="store_true",
                    help="small single-wave shape (the bench embedding)")
    ap.add_argument("--json", dest="json_out", default=None)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    witness = None
    if os.environ.get("KSS_TPU_LOCK_WITNESS") == "1":
        # install BEFORE the simulator package creates its locks
        from tools.analysis import lockwitness

        witness = lockwitness.install()
    verdict = chaos_verdict(seeds=args.seeds, seed_base=args.seed_base,
                            quick=args.quick, witness=witness)
    print(json.dumps(verdict, indent=2))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(verdict, fh, indent=2)
    if not verdict["ok"]:
        bad = verdict["failures"][0].split(":")[0] if verdict["failures"] \
            else f"seed {args.seed_base}"
        print(f"chaos: FAIL — reproduce with: KSS_TPU_LOCK_WITNESS=1 "
              f"JAX_PLATFORMS=cpu python -m tools.chaos --seeds 1 "
              f"--seed-base {bad.split()[-1]}", file=sys.stderr)
        for p in verdict.get("dumps") or []:
            print(f"chaos: black-box post-mortem dump: {p}",
                  file=sys.stderr)
        return 1
    print(f"chaos: ok — {len(verdict['seeds'])} seeds, "
          f"{verdict['injected_total']} faults injected, "
          f"{verdict['seconds']}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
