"""Observability smoke gate: `make obs-smoke` / `python -m tools.obs_smoke`.

The causal-telemetry proof in one process: arms a ONE-RULE fault plan,
runs a single engine wave UNDER AN EXPLICIT TRACE ID (the same
`trace_scope` the HTTP server enters for a stamped request), lets the
retry budget of 0 abort the wave, and asserts the one trace id threads
every observability surface:

  * tracer spans — the wave/speculative spans carry the id as an attr;
  * the black-box post-mortem dump — its events carry the id, and its
    embedded telemetry-history window passes validate_dump's schema
    check (columns rectangular, timestamps aligned);
  * the Perfetto export — filtering by the id returns the wave's spans
    plus the black-box instants.

This is the cheapest end-to-end proof of causal correlation
(docs/metrics.md "History & correlation") — `make test` runs it before
the tier-1 suite.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile

TRACE_ID = "obs-smoke-trace"


def _fail(msg: str) -> int:
    print(f"obs-smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    dump_dir = tempfile.mkdtemp(prefix="kss-obs-smoke-")
    plan = {"seed": 7, "rules": [
        {"seam": "replay.decision_fetch", "nth": 2, "error": "runtime"},
    ]}
    plan_path = os.path.join(dump_dir, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump(plan, fh)
    # env BEFORE the simulator imports: faults arms KSS_TPU_FAULT_PLAN
    # at module load, and the toggles the assertions depend on must not
    # be overridden by an inherited KSS_TPU_HISTORY=0 / _BLACKBOX=0
    os.environ["KSS_TPU_FAULT_PLAN"] = "@" + plan_path
    os.environ["KSS_TPU_BLACKBOX_DIR"] = dump_dir
    os.environ["KSS_TPU_WAVE_MAX_RETRIES"] = "0"
    os.environ["KSS_TPU_SPECULATIVE"] = "1"
    os.environ["KSS_TPU_BLACKBOX"] = "1"
    os.environ["KSS_TPU_HISTORY"] = "1"

    from kube_scheduler_simulator_tpu.cluster.store import ObjectStore
    from kube_scheduler_simulator_tpu.framework.engine import SchedulerEngine
    from kube_scheduler_simulator_tpu.models.workloads import (
        make_nodes, make_pods)
    from kube_scheduler_simulator_tpu.plugins.registry import PluginSetConfig
    from kube_scheduler_simulator_tpu.utils.blackbox import (
        FEEDER, validate_dump)
    from kube_scheduler_simulator_tpu.utils.faults import InjectedFault
    from kube_scheduler_simulator_tpu.utils.tracing import TRACER

    store = ObjectStore()
    for n in make_nodes(6, seed=1):
        store.create("nodes", n)
    for p in make_pods(24, seed=2):
        store.create("pods", p)
    engine = SchedulerEngine(
        store, plugin_config=PluginSetConfig(enabled=["NodeResourcesFit"]),
        chunk=8)
    FEEDER.sample()  # pre-wave ring row: the dump's window has a baseline
    surfaced = None
    try:
        with TRACER.trace_scope(TRACE_ID):
            engine.schedule_pending()
    except InjectedFault as e:
        surfaced = e
    finally:
        engine.close()
    if surfaced is None:
        return _fail("the armed fault never surfaced "
                     "(retry budget 0 should abort the wave)")

    # 1. spans: the wave's span tree carries the trace id as an attr
    traced_spans = [ev for ev in TRACER.events(limit=500)
                    if ev.get("trace_id") == TRACE_ID]
    if not traced_spans:
        return _fail("no tracer span carries the trace id "
                     f"{TRACE_ID!r} — trace_scope is not folding into "
                     "span attrs")

    # 2. the post-mortem dump: events stamped with the id + an embedded
    #    history window that validates (shape-checked by validate_dump)
    files = sorted(glob.glob(os.path.join(dump_dir, "blackbox-*.json")))
    if not files:
        return _fail(f"no dump landed in {dump_dir}")
    with open(files[-1], encoding="utf-8") as fh:
        doc = json.load(fh)
    try:
        res = validate_dump(doc, require_fault=True, require_rounds=True)
    except ValueError as e:
        return _fail(f"malformed dump {files[-1]}: {e}")
    traced_events = [ev for ev in doc["events"]
                     if ev.get("trace_id") == TRACE_ID
                     or TRACE_ID in (ev.get("traces") or ())]
    if not traced_events:
        return _fail("no black-box event in the dump carries the trace "
                     f"id {TRACE_ID!r}")
    hist = doc.get("history")
    if not isinstance(hist, dict) or not hist.get("index"):
        return _fail("the dump's embedded history window is missing or "
                     "empty — the feeder never populated the ring")

    # 3. Perfetto: filtering the export by the id returns the wave
    pf = TRACER.perfetto(trace_id=TRACE_ID)
    tevs = pf.get("traceEvents") or []
    pf_spans = [ev for ev in tevs if ev.get("ph") == "X"]
    pf_instants = [ev for ev in tevs if ev.get("ph") == "i"]
    if not pf_spans:
        return _fail("perfetto(trace_id=...) returned no spans for "
                     f"{TRACE_ID!r}")
    if not pf_instants:
        return _fail("perfetto(trace_id=...) returned no black-box "
                     f"instant events for {TRACE_ID!r}")

    print(json.dumps({
        "ok": True,
        "trace_id": TRACE_ID,
        "dump": files[-1],
        "reason": doc["reason"],
        "traced_spans": len(traced_spans),
        "traced_dump_events": len(traced_events),
        "history_rows": len(hist["index"]),
        "history_series": len(hist.get("series") or {}),
        "perfetto_spans": len(pf_spans),
        "perfetto_instants": len(pf_instants),
        "event_kinds": res["kinds"],
    }))
    print(f"obs-smoke: ok — trace {TRACE_ID!r} threads "
          f"{len(traced_spans)} spans, {len(traced_events)} dump events, "
          f"{len(pf_spans)}+{len(pf_instants)} perfetto events; history "
          f"window {len(hist['index'])} rows x "
          f"{len(hist.get('series') or {})} series")
    return 0


if __name__ == "__main__":
    sys.exit(main())
