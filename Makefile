# kube-scheduler-simulator_tpu build/test entry points.
#
# The framework is pure Python + JAX except the native annotation codec
# (kube_scheduler_simulator_tpu/native/annotation_codec.cpp), which the
# loader also auto-builds on first use; `make codec` is the explicit
# recipe.

PY ?= python

.PHONY: codec test bench smoke clean parity-fullscale multichip-scaling host-probe

# measurement artifacts (committed under docs/bench/; see BASELINE.md)
parity-fullscale:
	JAX_PLATFORMS=cpu $(PY) docs/bench/parity_fullscale.py

multichip-scaling:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	    $(PY) docs/bench/multichip_scaling.py

host-probe:
	$(PY) docs/bench/host_page_backing.py

codec:
	$(PY) -c "from kube_scheduler_simulator_tpu.native import build_codec; print(build_codec())"

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

smoke:
	$(PY) bench.py --smoke

clean:
	rm -f kube_scheduler_simulator_tpu/native/_annotation_codec.so
	find . -name __pycache__ -type d -exec rm -rf {} +
