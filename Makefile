# kube-scheduler-simulator_tpu build/test entry points.
#
# The framework is pure Python + JAX except the native annotation codec
# (kube_scheduler_simulator_tpu/native/annotation_codec.cpp), which the
# loader also auto-builds on first use; `make codec` is the explicit
# recipe.

PY ?= python

.PHONY: codec native-asan native-tsan test test-asan test-tsan analyze \
        bench bench-check bench-gang bench-serve bench-spec bench-fuse \
        bench-multichip bench-scale bench-soak blackbox-smoke obs-smoke \
        smoke chaos \
        clean \
        parity-fullscale parity-fullscale-device multichip-scaling \
        host-probe tpu-watch

# measurement artifacts (committed under docs/bench/; see BASELINE.md)
parity-fullscale:
	JAX_PLATFORMS=cpu $(PY) docs/bench/parity_fullscale.py

# full-scale byte-parity ON the device backend (round-4 verdict #5);
# requires a live accelerator tunnel
parity-fullscale-device:
	$(PY) docs/bench/parity_fullscale.py \
	    docs/bench/r05-parity-fullscale-tpu.json --device

# background tunnel-recovery watcher: probes device init every ~10 min,
# runs bench.py on revival until a non-fallback TPU artifact lands, then
# captures the on-device full-scale parity artifact and exits
tpu-watch:
	nohup bash docs/bench/tpu_watch.sh > /tmp/tpu_watch_out.log 2>&1 &

multichip-scaling:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	    $(PY) docs/bench/multichip_scaling.py

# CI-enforceable multichip gate: run the 8-virtual-device scaling
# harness on the DEVICE-RESIDENT replay path (the default) and assert it
# actually sharded with full byte-parity — skipped=true or a parity
# mismatch exits nonzero (docs/wave-pipeline.md device-residency stage)
bench-multichip:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	    $(PY) docs/bench/multichip_scaling.py /tmp/bench_multichip.json
	$(PY) -c "import json; d = json.load(open('/tmp/bench_multichip.json')); \
	    assert not d.get('skipped'), 'multichip harness skipped: %s' % d.get('skip_reason'); \
	    assert d.get('all_parity_ok') is True, 'sharded parity failed'; \
	    assert d.get('result_mode') == 'device_resident', d.get('result_mode'); \
	    print('bench-multichip: ok=true skipped=false (device-resident path, %d devices)' % d['devices'])"

# CI-enforceable columnar scale gate: the 25k/50k/100k-node curve on the
# columnar data plane (docs/data-plane.md) — every point parity-pinned
# against the dict plane, the 100k workload build >=3x over the dict
# baseline (same-process interleaved A/B), and an unchanged node set
# must reuse the node table, never rebuild it
bench-scale:
	JAX_PLATFORMS=cpu $(PY) docs/bench/multichip_scaling.py --scale \
	    /tmp/bench_scale.json
	$(PY) -c "import json; d = json.load(open('/tmp/bench_scale.json')); \
	    assert d['all_parity_ok'], 'columnar-vs-dict parity failed'; \
	    assert d['never_rebuilt_on_unchanged_nodes'], 'node table rebuilt on an unchanged node set'; \
	    assert d['all_delta_patched'], 'bounded node delta did not patch'; \
	    assert d['scale_100k_build_speedup_vs_dict'] >= 3, 'speedup %.2fx < 3x' % d['scale_100k_build_speedup_vs_dict']; \
	    print('bench-scale: ok=true all_parity_ok=true (100k: %.1fx build, %.1f cycles/s, %.0fMB RSS)' \
	        % (d['scale_100k_build_speedup_vs_dict'], d['scale_100k_cycles_per_sec'], d['scale_100k_host_rss_mb']))"

# CI-enforceable autopilot soak gate (docs/autopilot.md): sustained
# multi-session churn + overload against a live server with the
# controller ON — the standard tenant's p99 stays inside the SLO
# target, every shed response carries Retry-After, the shed lifts when
# the overload stops, and the degradation ladder recovers to rung 0
bench-soak:
	JAX_PLATFORMS=cpu $(PY) -m tools.soak /tmp/bench_soak.json
	$(PY) -c "import json; d = json.load(open('/tmp/bench_soak.json')); \
	    assert d['ok'], d['failures']; \
	    assert d['soak_p99_wave_seconds'] <= d['slo_target_p99_s'], \
	        'std p99 %.3fs over target' % d['soak_p99_wave_seconds']; \
	    assert d['all_shed_had_retry_after'], 'shed without Retry-After'; \
	    assert d['soak_recovered_to_rung0'], 'ladder pinned degraded'; \
	    assert d['history_breach_before_shed'] and d['history_shed_lift_recorded'], \
	        'breach->shed->recovery not reconstructible from the history ring'; \
	    assert d['shed_evidence_checked'] >= 1, 'no shed evidence checked against the ring'; \
	    print('bench-soak: ok=true (p99 %.3fs, shed rate %.2f, %d decisions, %d evidence rows ring-checked)' \
	        % (d['soak_p99_wave_seconds'], d['soak_shed_rate'], \
	           d['autopilot']['decisions'], d['shed_evidence_checked']))"

host-probe:
	$(PY) docs/bench/host_page_backing.py

codec:
	$(PY) -c "from kube_scheduler_simulator_tpu.native import build_codec; print(build_codec())"

# sanitizer build of the codec (address+undefined); the slow test in
# tests/test_native_asan.py runs the codec suite against it via
# KSS_TPU_NATIVE_SO + LD_PRELOAD of the ASan runtime
native-asan:
	$(PY) -c "from kube_scheduler_simulator_tpu.native import build_codec, ASAN_FLAGS; print(build_codec('kube_scheduler_simulator_tpu/native/_annotation_codec_asan.so', extra_flags=ASAN_FLAGS))"

test-asan:
	$(PY) -m pytest tests/test_native_asan.py -q -m slow

# ThreadSanitizer build of the codec; the slow test in
# tests/test_native_tsan.py runs the 4-thread concurrent chunk-decode
# soak against it (suppressions scope TSan to the codec's own threads —
# see native/tsan_suppressions.txt and docs/static-analysis.md)
native-tsan:
	$(PY) -c "from kube_scheduler_simulator_tpu.native import build_codec, TSAN_FLAGS; print(build_codec('kube_scheduler_simulator_tpu/native/_annotation_codec_tsan.so', extra_flags=TSAN_FLAGS))"

test-tsan:
	$(PY) -m pytest tests/test_native_tsan.py -q -m slow

# the kss-analyze static suite (docs/static-analysis.md): lock
# discipline, device purity, observability conformance.  Pure AST — no
# JAX import, no device; exits nonzero on any finding not suppressed
# in-source or grandfathered in tools/analysis/baseline.json
analyze:
	$(PY) -m tools.analysis

# wave black-box smoke gate (docs/metrics.md post-mortem dumps): arm a
# one-rule fault plan via KSS_TPU_FAULT_PLAN, run a wave with the retry
# budget at 0, and assert a schema-valid post-mortem dump lands in
# KSS_TPU_BLACKBOX_DIR (fault trip + speculative round history +
# counter deltas + device fingerprint) — a crashed wave must ship its
# own evidence
blackbox-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.blackbox_smoke

# causal-telemetry smoke gate (docs/metrics.md "History & correlation"):
# run one faulted wave under an explicit trace id and assert the id
# threads the tracer spans, the post-mortem dump's events, and the
# Perfetto export (spans + black-box instants), and that the dump's
# embedded history window validates — one trace id, every surface
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.obs_smoke

test: analyze blackbox-smoke obs-smoke
	$(PY) -m pytest tests/ -q -m "not slow"

bench:
	$(PY) bench.py

# compare the newest BENCH_*.json round against the previous one on the
# key serving metrics; exits nonzero on >15% regression (docs/metrics.md)
bench-check:
	$(PY) docs/bench/bench_check.py

# gang-workload shape (docs/gang-scheduling.md): PodGroup co-scheduling
# through the vectorized quorum pass, printing the gang_* counters so
# BENCH rounds can track gang throughput
bench-gang:
	$(PY) bench.py --gang

# multi-session serving shape (docs/api.md sessions surface): K>=4
# concurrent isolated sessions on one device, reporting aggregate + p99
# per-session cycles/s and the cross-session compile-cache hit rate
# (asserted >= (K-1)/K: each scan shape compiles once per process)
bench-serve:
	$(PY) bench.py --serve | tee /tmp/bench_serve.json
	$(PY) -c "import json; d = [json.loads(l) for l in open('/tmp/bench_serve.json') if l.startswith('{')][-1]; \
	    s = d['extra']['serve']; cc = s['compile_cache']; \
	    assert s['sessions'] >= 4, s['sessions']; \
	    assert cc['hit_rate'] >= cc['floor'], (cc, 'hit rate under (K-1)/K'); \
	    print('bench-serve: %d sessions, warm aggregate %.0f cycles/s, p99 %.0f, cache hit rate %.2f (floor %.2f)' \
	        % (s['sessions'], s['warm']['aggregate_cycles_per_sec'], s['warm']['p99_session_cycles_per_sec'], cc['hit_rate'], cc['floor']))"

# speculative-wave A/B (docs/wave-pipeline.md speculative-wave stage):
# the default speculative wave vs the KSS_TPU_SPECULATIVE=0 sequential
# scan, same process, at the 10k x 5k shape — low-contention
# reserved-slot scenario (measured ~1.5x on an idle 2-core geometry;
# the gate floors at 1.4x so shared-host noise can't flake it, and
# bench_check gates the committed trajectory) with accept rate >= 0.9,
# plus the contention-heavy broad-feasibility variant exercising the
# scan fallback
bench-spec:
	$(PY) bench.py --spec | tee /tmp/bench_spec.json
	$(PY) -c "import json; d = [json.loads(l) for l in open('/tmp/bench_spec.json') if l.startswith('{')][-1]; \
	    s = d['extra']['speculative']; low = s['low_contention']; \
	    assert low['speedup'] >= 1.4, (low, 'speculative speedup under the 1.4x noise floor (measured ~1.5x idle)'); \
	    assert low['accept_rate'] >= 0.9, (low, 'low-contention accept rate under 0.9'); \
	    assert s['contended']['fallbacks'] >= 1, (s['contended'], 'contended variant never exercised the scan fallback'); \
	    print('bench-spec: %.1fx vs scan (%.0f vs %.0f cycles/s), accept rate %.2f over %d rounds; contended: %.2fx, accept %.2f, %d fallback(s)' \
	        % (low['speedup'], low['speculative_cycles_per_sec'], low['sequential_cycles_per_sec'], low['accept_rate'], low['rounds'], \
	           s['contended']['speedup'], s['contended']['accept_rate'], s['contended']['fallbacks']))"

# cross-session fused dispatch A/B (docs/wave-pipeline.md fused-dispatch
# stage): K sessions' speculative rounds stacked into one vmapped device
# call vs KSS_TPU_FUSE=0 time-sharing, asserting byte-identical
# per-session bindings/annotations in the same run.  The gate enforces
# the parity bar and that fused batches actually form (>= 1 fused device
# call per K) — NOT a speedup floor: on the 2-core CPU geometry the
# time-shared arm already parallelizes K solo calls across cores, so
# fusion measures ~0.5x at K=4 / ~0.8x at K=8 (docs/wave-pipeline.md
# states the mesh-dp projection: on a dp-extent mesh the stacked session
# axis lays over devices and the fused call IS the parallelism, minus
# K-1 dispatches).  bench_check tracks the committed trajectory.
bench-fuse:
	$(PY) bench.py --fuse | tee /tmp/bench_fuse.json
	$(PY) -c "import json; d = [json.loads(l) for l in open('/tmp/bench_fuse.json') if l.startswith('{')][-1]; \
	    allk = d['extra']['fuse']; \
	    ks = {k: v for k, v in allk.items() if 'parity_byte_identical' in v}; \
	    skipped = {k: v.get('error') for k, v in allk.items() if k not in ks}; \
	    assert ks, 'no fuse measurements landed'; \
	    assert all(v['parity_byte_identical'] for v in ks.values()), (ks, 'fused vs time-shared parity violated'); \
	    assert all(v['fused_device_calls'] >= 1 for v in ks.values()), (ks, 'no fused batches formed'); \
	    print('\n'.join('bench-fuse %s: SKIPPED (%s)' % kv for kv in skipped.items())); \
	    print('\n'.join('bench-fuse k=%s: fused %.0f vs time-shared %.0f aggregate cycles/s (%.2fx), p99 %.0f vs %.0f, %d fused calls, parity OK' \
	        % (k.lstrip('k'), v['fuse_aggregate_cycles_per_sec'], v['timeshared_aggregate_cycles_per_sec'], v['aggregate_speedup'], \
	           v['fuse_p99_session_cycles_per_sec'], v['timeshared_p99_session_cycles_per_sec'], v['fused_device_calls']) for k, v in sorted(ks.items())))"

# chaos gate (docs/fault-injection.md): concurrent multi-session waves
# under seeded fault plans at every seam, asserting completion via
# retry/degradation, bit-identical annotations vs the fault-free run,
# gang atomicity, per-session isolation, and no lock-order cycles under
# the runtime witness.  Deterministic: a failure prints the seed and
# the exact reproducing command.  Also runs as the slow-marked tier-2
# suite tests/test_chaos.py, and a quick verdict rides every bench
# round (extra.chaos; bench-check refuses rounds whose chaos failed).
chaos:
	KSS_TPU_LOCK_WITNESS=1 JAX_PLATFORMS=cpu $(PY) -m tools.chaos --seeds 3

smoke:
	$(PY) bench.py --smoke

clean:
	rm -f kube_scheduler_simulator_tpu/native/_annotation_codec.so \
	    kube_scheduler_simulator_tpu/native/_annotation_codec_asan.so \
	    kube_scheduler_simulator_tpu/native/_annotation_codec_tsan.so
	find . -name __pycache__ -type d -exec rm -rf {} +
