"""Example guest plugin (docs/integrate-your-scheduler.md): enable by
declaring it in scheduler.yaml pluginConfig with guestURL + multiPoint."""

from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin


class Plugin(CustomPlugin):
    default_weight = 1

    def filter(self, pod, node):
        # reject nodes labeled quarantine=true
        labels = (node.get("metadata") or {}).get("labels") or {}
        if str(labels.get("quarantine", "")).lower() == "true":
            return "node is quarantined"
        return None

    def score(self, pod, node):
        # prefer nodes with more allocatable pods
        alloc = ((node.get("status") or {}).get("allocatable") or {})
        return int(str(alloc.get("pods", "0")))
