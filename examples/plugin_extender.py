"""Plugin-extender sample: record extra data onto the pod per cycle.

The reference's sample extender wraps NodeResourcesFit's PreFilter and
stores what the plugin wrote into the cycle state as a custom annotation
via the SimulatorHandle (reference:
simulator/docs/sample/plugin-extender/extender.go AfterPreFilter +
handle.AddCustomResult).  The analogue here observes the finished cycle
and records the pod's total requested cpu next to the standard result
annotations — it lands on the pod as
`sample.simulator.example.com/requested-cpu`.

Run:  python examples/plugin_extender.py
"""

from kube_scheduler_simulator_tpu.scheduler.debuggable import (
    PluginExtender,
    new_scheduler_command,
)


class RequestedCpuRecorder(PluginExtender):
    KEY = "sample.simulator.example.com/requested-cpu"

    def after_cycle(self, pod, annotations, result_store):
        meta = pod.get("metadata") or {}
        total_m = 0
        for c in (pod.get("spec") or {}).get("containers", []):
            v = ((c.get("resources") or {}).get("requests") or {}).get("cpu", "0")
            total_m += int(float(v[:-1])) if v.endswith("m") else int(float(v) * 1000)
        result_store.add_custom_result(
            meta.get("namespace") or "default", meta.get("name", ""),
            self.KEY, f"{total_m}m")


if __name__ == "__main__":
    di, server = new_scheduler_command(
        with_plugin_extenders={"NodeResourcesFit": RequestedCpuRecorder()})
    print(f"simulator with RequestedCpuRecorder on :{server.port}")
    server.start(block=True)
