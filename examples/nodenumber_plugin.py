"""NodeNumber — the classic out-of-tree sample plugin, TPU-simulator style.

Favors nodes whose name ends in the same single digit as the pod's name
(reverse=True inverts the preference); non-digit suffixes score 0 and
never fail the cycle.  The reference ships this sample as a Go plugin
compiled into a debuggable scheduler (reference:
simulator/docs/sample/nodenumber/plugin.go, wired via WithPlugin in
docs/integrate-your-scheduler.md); here it is a CustomPlugin registered
through new_scheduler_command(with_plugins=[...]) — its Score results
are recorded into score-result/finalscore-result like any in-tree
plugin's.

Run:  python examples/nodenumber_plugin.py
"""

from kube_scheduler_simulator_tpu.plugins.custom import CustomPlugin


class NodeNumber(CustomPlugin):
    name = "NodeNumber"
    default_weight = 1

    def __init__(self, reverse: bool = False):
        self.reverse = reverse

    def score(self, pod: dict, node: dict) -> int:
        pod_suffix = (pod.get("metadata", {}).get("name") or "")[-1:]
        node_suffix = (node.get("metadata", {}).get("name") or "")[-1:]
        if not (pod_suffix.isdigit() and node_suffix.isdigit()):
            return 0
        match = pod_suffix == node_suffix
        return 10 if match != self.reverse else 0


if __name__ == "__main__":
    from kube_scheduler_simulator_tpu.scheduler.debuggable import new_scheduler_command

    di, server = new_scheduler_command(with_plugins=[NodeNumber()])
    print(f"simulator with NodeNumber on :{server.port}")
    server.start(block=True)
